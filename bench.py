"""Benchmark harness — the measurement frame of BASELINE.md.

Metric of record (BASELINE.json:2): CICIDS2017 end-to-end training
wall-clock at macro-F1 parity, over the five reference configs [B:6-12]:

  1  LogisticRegression binary (benign vs attack, 2-day subset)
  2  MultilayerPerceptronClassifier 15-class  (the flagship / default)
  3  RandomForestClassifier + ChiSqSelector
  4  GBTClassifier one-vs-rest, all days (15-class)
  5  Structured-streaming inference micro-batches (rows/s)

plus the post-paper configs: 6 (fused vs staged serving, r9), 7
(the r11 live-model lifecycle arc on a drifting stream — incumbent
degrades, drift detected, candidate refit online and promoted,
macro-F1 recovers; detection latency and swap downtime journaled),
8 (the r12 multi-tenant ServeDaemon at 10+ tenants), and 9 (the r14
raw-capture flow engine: replayed capture → keyed windows → features
→ classify vs the precomputed-CSV path on the same rows).

No Spark and no real CICIDS2017 exist in-image (SURVEY.md §6), so the
workload is the schema-locked synthetic generator (real day CSVs drop in
unchanged) and the baseline is a CPU proxy (sklearn, same algorithm family
and budget — labeled as a proxy).  Since r5 the proxy is measured IN THE
SAME INVOCATION on the same split (``paired: true`` in the output/journal)
so host drift cancels inside each ratio; ``--no-pair`` falls back to the
cached ``baseline_proxy.json`` (measured with ``--measure-baseline``).

stdout is ONE JSON line for the selected config (default: 2):
  {"metric": ..., "value": <train_wall_clock_s>, "unit": "s",
   "vs_baseline": <baseline_s / ours_s>, ...}

``value`` is steady-state fit time (a same-shape warmup fit first: XLA
compile is one-off per shape and cached across fits; the cold time is also
reported).  ``--config all`` prints every config, one JSON line each, the
flagship line LAST (so the driver's one-line contract still reads config 2).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
BASELINE_CACHE = os.path.join(REPO, "baseline_proxy.json")
RUNS_JOURNAL = os.path.join(REPO, "bench_runs.jsonl")


def _git_sha() -> str:
    import subprocess

    try:
        return subprocess.run(
            ["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _git_dirty() -> bool:
    """True when the working tree differs from HEAD — journal provenance
    (a run at sha X with uncommitted changes is NOT the code at X; the
    08:02Z 2026-07-31 gmm rows were exactly that case)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", REPO, "status", "--porcelain"],
            capture_output=True, text=True, timeout=10,
        ).stdout
        # append-only evidence files are not code: the journal's own
        # append must not flag the rest of a multi-line run as dirty
        evidence = (
            "bench_runs.jsonl", "tpu_probe_log.jsonl",
            "tpu_queue_log.jsonl", "PROGRESS.jsonl", "baseline_proxy.json",
        )
        return any(
            not line.startswith("??")
            and not line.strip().endswith(evidence)
            for line in out.splitlines()
        )
    except Exception:
        return True


# watermark so each journal record reports only ITS OWN resilience
# activity: a --config all sweep runs several configs in one process,
# and config 1's retries must not show up as evidence against config 5
_resilience_mark = {"step": -1, "dropped": 0}


def _resilience_summary():
    """Health/breaker evidence for the journal: per-type counts of the
    structured resilience events SINCE the previous journal record
    (event ``step`` watermark), ring evictions in the same window, and
    any breaker that is not a pristine closed one.  None when the
    window was clean — a result with retries or open breakers behind it
    is not the same evidence as one without."""
    try:
        from sntc_tpu.resilience import (
            breakers_snapshot,
            events_dropped,
            recent_events,
        )
    except Exception:
        return None
    counts: dict = {}
    max_step = _resilience_mark["step"]
    for e in recent_events():
        step = e.get("step", 0)
        if step <= _resilience_mark["step"]:
            continue
        max_step = max(max_step, step)
        name = e.get("event", "unknown")
        counts[name] = counts.get(name, 0) + 1
    dropped_now = events_dropped()
    # clear_events() resets the counter; never report a negative delta
    dropped = max(0, dropped_now - _resilience_mark["dropped"])
    _resilience_mark["step"] = max_step
    _resilience_mark["dropped"] = dropped_now
    breakers = {
        site: snap
        for site, snap in breakers_snapshot().items()
        if snap["state"] != "closed" or snap["open_count"]
    }
    if not counts and not breakers and not dropped:
        return None
    out = {"event_counts": counts, "events_dropped": dropped}
    if breakers:
        out["breakers"] = breakers
    return out


# watermark for the metrics-registry journal field: a --config all
# sweep shares one process registry, and each record must report only
# ITS OWN window's activity (the _resilience_mark discipline)
_obs_mark: dict = {"flat": {}}


def _obs_flatten() -> dict:
    """The process metrics registry as flat ``name{k=v,...}`` → value
    (histograms contribute ``:count``/``:sum``) — the journalable
    form of a snapshot."""
    from sntc_tpu.obs.metrics import registry

    flat: dict = {}
    for name, metric in registry().snapshot().items():
        for s in metric["series"]:
            labels = s["labels"]
            key = name + (
                "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())
                ) + "}"
                if labels else ""
            )
            if metric["type"] == "histogram":
                flat[key + ":count"] = s["count"]
                flat[key + ":sum"] = round(s["sum"], 6)
            else:
                flat[key] = (
                    round(s["value"], 6)
                    if isinstance(s["value"], float)
                    and not float(s["value"]).is_integer()
                    else int(s["value"])
                )
    return flat


def _obs_summary():
    """Registry activity for the journal: nonzero deltas of every
    metric series since the previous journal record.  None when the
    window was quiet."""
    try:
        flat = _obs_flatten()
    except Exception:
        return None
    prev = _obs_mark["flat"]
    delta = {}
    for k, v in flat.items():
        d = v - prev.get(k, 0)
        if d:
            delta[k] = round(d, 6) if isinstance(d, float) else d
    _obs_mark["flat"] = flat
    return delta or None


def _journal_run(cfg: str, line: dict) -> None:
    """Append the full machine-written record of this invocation to the
    COMMITTED ``bench_runs.jsonl`` — the auditable raw evidence behind
    every BASELINE.md table row (config, cold+warm, platform, quality,
    timestamp, git SHA).  Opt-out: ``BENCH_NO_JOURNAL=1``."""
    if os.environ.get("BENCH_NO_JOURNAL"):
        return
    record = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": _git_sha(),
        "git_dirty": _git_dirty(),
        "config": cfg,
        "bench_rows_env": os.environ.get("BENCH_ROWS"),
        **line,
    }
    # a line that already carries its own evidence (an --isolate child
    # shipped its ring through stdout) must not be overwritten with the
    # parent's — the parent ring never saw the child's events
    if "resilience" not in record:
        resilience = _resilience_summary()
        if resilience is not None:
            record["resilience"] = resilience
    # the metrics-registry window delta rides every journal record: the
    # same counters an operator would scrape from --metrics-out, scoped
    # to this config's run (obs satellite of r13)
    if "obs" not in record:
        obs = _obs_summary()
        if obs is not None:
            record["obs"] = obs
    with open(RUNS_JOURNAL, "a") as f:
        f.write(json.dumps(record) + "\n")

SEED = 7
MLP_LAYERS = [78, 64, 15]
MLP_MAX_ITER = 100
LR_MAX_ITER = 100
# depth 10: on 80%-benign 15-class data a depth-5 greedy forest cannot
# exceed macro-F1 ~0.35 no matter how separable the classes are (it
# spends its split budget on the large classes), so the config-3 quality
# bar would certify nothing; at depth 10 both our RF and the proxy land
# ~0.8 — a discriminative regime where a broken grower shows
RF_TREES = int(os.environ.get("BENCH_RF_TREES", 20))
RF_DEPTH = int(os.environ.get("BENCH_RF_DEPTH", 10))
CHISQ_TOP = 40
GBT_ROUNDS, GBT_DEPTH = 10, 4
# 128 quantile bins ≈ sklearn's exact splits in macro-F1 on this workload
# (32, Spark's default, costs ~0.09 macro-F1); histograms stay tiny
GBT_BINS = 128

DEFAULT_ROWS = {
    "1": int(os.environ.get("BENCH_ROWS", 500_000)) // 2,
    "2": int(os.environ.get("BENCH_ROWS", 500_000)),
    "3": int(os.environ.get("BENCH_ROWS", 500_000)) // 2,
    "4": int(os.environ.get("BENCH_ROWS", 500_000)) // 4,
    "5": int(os.environ.get("BENCH_ROWS", 500_000)) // 4,
    "6": int(os.environ.get("BENCH_ROWS", 500_000)) // 4,
    "7": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "8": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "9": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "10": int(os.environ.get("BENCH_ROWS", 500_000)) // 4,
    "11": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "12": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "13": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "14": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "15": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "16": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "17": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
    "18": int(os.environ.get("BENCH_ROWS", 500_000)) // 8,
}


def _dataset(n_rows: int, binary: bool = False):
    from sntc_tpu.data import clean_flows, generate_frame

    # 0.5% tail-class floor: at bench scale every class has enough rows
    # to be learnable (real CICIDS2017 at 2.8M rows gives Bot/Web-attack
    # classes a comparable share), so macro-F1 differences are real
    df = clean_flows(generate_frame(n_rows, seed=SEED,
                                    min_class_fraction=0.005))
    if binary:
        df = df.with_column(
            "Label",
            np.where(
                df["Label"].astype(str) == "BENIGN", "benign", "attack"
            ).astype(object),
        )
    return df.random_split([0.8, 0.2], seed=0)


def _feature_stages(mesh, with_scaler=True):
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.feature import StandardScaler, StringIndexer, VectorAssembler

    stages = [
        # skip: a label unseen in train (possible in small subsets; Spark
        # apps set this for the same reason) drops the row at transform
        StringIndexer(inputCol="Label", outputCol="label",
                      handleInvalid="skip"),
        VectorAssembler(inputCols=CICIDS2017_FEATURES, outputCol="rawFeatures"),
    ]
    if with_scaler:
        stages.append(
            StandardScaler(mesh=mesh, inputCol="rawFeatures",
                           outputCol="features", withMean=True)
        )
    return stages


def _timed_fit(build_pipeline, train):
    t0 = time.perf_counter()
    build_pipeline().fit(train)
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    model = build_pipeline().fit(train)
    warm = time.perf_counter() - t0
    return model, warm, cold


def _evaluate(model, test, mesh, metric="macroF1"):
    from sntc_tpu.evaluation import MulticlassClassificationEvaluator

    return MulticlassClassificationEvaluator(
        metricName=metric, mesh=mesh
    ).evaluate(model.transform(test))


# ---------------------------------------------------------------------------
# per-config benches: each returns {metric, value(s), quality, n_rows}
# ---------------------------------------------------------------------------


def bench_config1(n_rows, mesh):
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.evaluation import BinaryClassificationEvaluator
    from sntc_tpu.models import LogisticRegression

    train, test = _dataset(n_rows, binary=True)

    def build():
        return Pipeline(stages=_feature_stages(mesh) + [
            LogisticRegression(mesh=mesh, maxIter=LR_MAX_ITER, regParam=1e-4)
        ])

    model, warm, cold = _timed_fit(build, train)
    auc = BinaryClassificationEvaluator().evaluate(model.transform(test))
    return {
        "metric": "cicids2017_binary_lr_train_wall_clock",
        "_datasets": (train, test),
        "value": warm, "cold_value": cold,
        "quality": {"areaUnderROC": auc},
        "n_rows": train.num_rows,
    }


def bench_config2(n_rows, mesh):
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.models import MultilayerPerceptronClassifier

    train, test = _dataset(n_rows)

    def build():
        return Pipeline(stages=_feature_stages(mesh) + [
            MultilayerPerceptronClassifier(
                mesh=mesh, layers=MLP_LAYERS, maxIter=MLP_MAX_ITER, seed=0
            )
        ])

    model, warm, cold = _timed_fit(build, train)
    f1 = _evaluate(model, test, mesh)
    return {
        "metric": "cicids2017_15class_mlp_pipeline_train_wall_clock",
        "_datasets": (train, test),
        "value": warm, "cold_value": cold,
        "quality": {"macro_f1": f1},
        "n_rows": train.num_rows,
    }


def bench_config3(n_rows, mesh):
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.feature import ChiSqSelector
    from sntc_tpu.models import RandomForestClassifier

    train, test = _dataset(n_rows)

    def build():
        return Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
            ChiSqSelector(mesh=mesh, numTopFeatures=CHISQ_TOP,
                          featuresCol="rawFeatures", labelCol="label",
                          outputCol="features"),
            RandomForestClassifier(mesh=mesh, numTrees=RF_TREES,
                                   maxDepth=RF_DEPTH, seed=0),
        ])

    model, warm, cold = _timed_fit(build, train)
    f1 = _evaluate(model, test, mesh)
    return {
        "metric": "cicids2017_rf_chisq_train_wall_clock",
        "_datasets": (train, test),
        "value": warm, "cold_value": cold,
        "quality": {"macro_f1": f1},
        "n_rows": train.num_rows,
    }


def bench_config4(n_rows, mesh):
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.models import GBTClassifier, OneVsRest

    train, test = _dataset(n_rows)

    def build():
        return Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
            OneVsRest(
                classifier=GBTClassifier(
                    mesh=mesh, maxIter=GBT_ROUNDS, maxDepth=GBT_DEPTH,
                    stepSize=0.1, seed=0, maxBins=GBT_BINS,
                ),
                featuresCol="rawFeatures",
            )
        ])

    model, warm, cold = _timed_fit(build, train)
    f1 = _evaluate(model, test, mesh)
    return {
        "metric": "cicids2017_gbt_ovr_train_wall_clock",
        "_datasets": (train, test),
        "value": warm, "cold_value": cold,
        "quality": {"macro_f1": f1},
        "n_rows": train.num_rows,
    }


BENCH5_SHAPE_BUCKETS = 256
# depth 3 + two staged reads: engine thread + delivery thread + two
# prefetch readers.  The win comes from the heavy GIL-releasing C++
# stages (pyarrow CSV parse and CSV write) overlapping — reads chain
# back-to-back on the staging pool while the delivery thread writes.
BENCH5_PIPELINE_DEPTH = 3
BENCH5_PREFETCH = 2
# micro-batch row counts cycle through three distinct sizes: a serial
# engine recompiles predict per size, the bucketed one compiles once per
# power-of-two bucket and then stays flat
BENCH5_SIZES = (2048, 1024, 512)

def _write_bench5_stream(in_dir, frame, passes=None, chunk_cycle=None):
    """THE config-5 synthetic stream: micro-batch CSV part files whose
    row counts cycle through ``chunk_cycle`` (default BENCH5_SIZES),
    ``passes`` passes over ``frame``.  One writer shared by the engine
    bench and the sklearn proxy so the two sides of the paired ratio
    can never drift apart (config 8 reuses it per tenant).  Returns
    the per-file row counts (len = file count, sum = total stream rows
    — the exact ledger; the engine's recentProgress ring keeps only
    the last 100 batches, so it cannot be the row source for long
    streams)."""
    import pyarrow.csv as pacsv

    from sntc_tpu.data import CICIDS2017_FEATURES

    cycle = chunk_cycle or BENCH5_SIZES
    os.makedirs(in_dir, exist_ok=True)
    sizes = []
    for _pass in range(passes or 1):
        i = 0
        while i < frame.num_rows:
            size = cycle[len(sizes) % len(cycle)]
            chunk = frame.slice(i, min(i + size, frame.num_rows))
            pacsv.write_csv(
                chunk.select(CICIDS2017_FEATURES).to_arrow(),
                os.path.join(in_dir, f"part_{len(sizes):05d}.csv"),
            )
            i += chunk.num_rows
            sizes.append(chunk.num_rows)
    return sizes


# each engine's stream is timed BENCH5_REPS times (fresh checkpoint/out
# dirs, same predictor), reps interleaved between the engines; the
# MEDIAN rep per engine is reported (best also journaled) — host-noise
# hygiene for a seconds-scale measurement on a shared box, symmetric
# for both engines.  The stream repeats the test split
# BENCH5_STREAM_PASSES times so each rep is long enough to average over
# short noise bursts.
BENCH5_REPS = 5
BENCH5_STREAM_PASSES = 2


def _read_sink_dir(out_dir):
    """All batch_*.csv of one engine's sink as a single Arrow table
    (shared by configs 5 and 6 — both compare full sink contents)."""
    import pyarrow as pa
    import pyarrow.csv as pacsv

    parts = [
        pacsv.read_csv(p)
        for p in sorted(glob.glob(os.path.join(out_dir, "batch_*.csv")))
    ]
    # header-only batch CSVs (a capture micro-batch that completed no
    # windows, config 9) infer null-typed columns that poison the
    # concat; they carry no rows, so drop them when any rows exist
    nonempty = [t for t in parts if t.num_rows]
    return pa.concat_tables(nonempty if nonempty else parts[:1])


def _sinks_match(a, b):
    """Row-for-row equality of two engines' full sink output."""
    if a.column_names != b.column_names or a.num_rows != b.num_rows:
        return False
    return all(
        np.array_equal(a.column(c).to_numpy(), b.column(c).to_numpy())
        for c in a.column_names
    )


def bench_config5(n_rows, mesh):
    """Streaming inference throughput: rows/s through the micro-batch
    engine over a REAL file stream — CSV micro-batches in, prediction
    CSVs out (model fit excluded — serving is the workload [B:11]).

    Runs the SAME synthetic stream through BOTH engines: the serial
    engine (``pipeline_depth=1``, no buckets) and the pipelined engine
    (prefetching source + shape-bucketed predict + overlapped sink
    delivery) — the r8 software-pipelining claim measured, not asserted.
    The sink writes the FULL enriched row (78 flow features +
    prediction), Spark's append-mode output of the transformed frame —
    which also makes the retire stage real work, not a one-column
    stub.  Micro-batch row counts cycle through three distinct sizes so
    the bucket path's compile cache is exercised;
    ``recompiles_after_warmup`` in the ``pipeline`` evidence field must
    stay 0.  The two engines' sink contents are compared row-for-row
    (``sink_match``)."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)
    # serving pipeline: drop the indexer, fold the scaler into the model
    serve_model = compile_serving(PipelineModel(stages=pipe.getStages()[1:]))

    def make_engine(tmp, name, in_dir, chunk_sizes, *, pipelined):
        """Warm one engine's predictor and return its run context.
        BOTH engines warm outside the timed window: one micro-batch
        through a throwaway query (process-global first-touch costs —
        pyarrow pools, jit, WAL/sink paths), then EVERY distinct chunk
        row count the stream contains straight through the predictor —
        including the ragged tail remainder, whose floor-bucket shape
        the cycling sizes alone would miss.  ONE predictor per engine
        across warmup and every measured rep, so compile_events is a
        single ledger."""
        predictor = BatchPredictor(
            serve_model,
            bucket_rows=BENCH5_SHAPE_BUCKETS if pipelined else 0,
        )
        warm = StreamingQuery(
            predictor, FileStreamSource(in_dir),
            CsvDirSink(os.path.join(tmp, f"warm_{name}"), durable=False),
            os.path.join(tmp, f"warmckpt_{name}"),
            max_batch_offsets=1, wal_mode="append",
        )
        warm._run_one_batch()
        warm.stop()
        for c in sorted(set(chunk_sizes)):
            predictor.predict_frame(test.slice(0, c))
        return {
            "name": name, "pipelined": pipelined,
            "predictor": predictor,
            "compiles_before": predictor.compile_events,
            "reps": [],
        }

    def run_once(tmp, eng, in_dir, rep, stream_rows, n_files):
        """One timed pass of the whole stream; records the rep."""
        name, pipelined = eng["name"], eng["pipelined"]
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        src = FileStreamSource(
            in_dir,
            prefetch_batches=BENCH5_PREFETCH if pipelined else 0,
        )
        q = StreamingQuery(
            eng["predictor"], src,
            # full enriched row (all 1-D cols); durable=False for BOTH
            # engines — page-cache publish, the pre-r8 sink semantics —
            # so the ratio isolates pipelining from the r8 fsync feature
            CsvDirSink(out_dir, durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=1, wal_mode="append",
            pipeline_depth=BENCH5_PIPELINE_DEPTH if pipelined else 1,
            overlap_sink=pipelined,
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        # exact row ledger from the stream writer (recentProgress keeps
        # only the last 100 batches); progress-sum fallback only if a
        # batch somehow didn't commit
        rows = (
            stream_rows
            if n_done == n_files
            else sum(p["numInputRows"] for p in q.recentProgress)
        )
        lat = np.asarray(
            [p["durationMs"] for p in q.recentProgress], np.float64
        )
        stats = q.pipeline_stats()
        q.stop()
        src.close()
        rec = {
            "out_dir": out_dir, "batches": n_done, "rows": rows,
            "dt": dt, "rows_per_s": rows / dt,
            "latency_ms_p50": float(np.percentile(lat, 50)),
            "latency_ms_p99": float(np.percentile(lat, 99)),
            "stats": stats,
        }
        eng.setdefault("reps", []).append(rec)
        return rec

    def finish_engine(eng):
        # MEDIAN rep = the reported measurement (robust to one noisy
        # window on a shared host, symmetric for both engines)
        reps = sorted(eng["reps"], key=lambda r: r["rows_per_s"])
        median = reps[len(reps) // 2]
        median["stats"]["recompiles_after_warmup"] = (
            eng["predictor"].compile_events - eng["compiles_before"]
        )
        median["stats"]["reps"] = BENCH5_REPS
        median["stats"]["best_rows_per_s"] = round(
            reps[-1]["rows_per_s"], 1
        )
        return median

    tmp = tempfile.mkdtemp()
    # intra-op pinned to ONE thread for BOTH engines: arrow's hidden
    # intra-file parse pool otherwise competes with the pipeline's
    # explicit inter-batch parallelism for the same few cores, and the
    # ratio would measure the host's core count, not engine structure.
    # With intra-op pinned, every stage costs its single-core cost and
    # the engines differ only in overlap — tf.data's inter-op-over-
    # intra-op discipline (arxiv 2101.12127); see docs/PERFORMANCE.md.
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)
    try:
        # one synthetic stream, micro-batch sizes cycling through three
        # distinct row counts (the shape-bucket workload); written once,
        # served by both engines
        in_dir = os.path.join(tmp, "in")
        chunk_sizes = _write_bench5_stream(
            in_dir, test, passes=BENCH5_STREAM_PASSES
        )
        stream_rows, n_files = sum(chunk_sizes), len(chunk_sizes)
        engines = [
            make_engine(tmp, "serial", in_dir, chunk_sizes,
                        pipelined=False),
            make_engine(tmp, "pipe", in_dir, chunk_sizes,
                        pipelined=True),
        ]
        # reps INTERLEAVE the two engines: host-speed drift on a shared
        # box lands on both sides of the ratio instead of biasing one
        for rep in range(BENCH5_REPS):
            for eng in engines:
                run_once(tmp, eng, in_dir, rep, stream_rows, n_files)
        serial, pipe_r = (finish_engine(e) for e in engines)
        sink_match = _sinks_match(
            _read_sink_dir(serial["out_dir"]),
            _read_sink_dir(pipe_r["out_dir"]),
        )
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)
    pipeline_evidence = {
        **pipe_r["stats"],
        "arrow_intra_op_threads": 1,
        "serial_rows_per_s": round(serial["rows_per_s"], 1),
        "speedup_vs_serial": _round_ratio(
            pipe_r["rows_per_s"] / serial["rows_per_s"]
        ),
        "serial_latency_ms_p50": round(serial["latency_ms_p50"], 3),
        "serial_latency_ms_p99": round(serial["latency_ms_p99"], 3),
        "sink_match": sink_match,
        "batch_sizes": list(BENCH5_SIZES),
    }
    return {
        "metric": "cicids2017_streaming_inference_rows_per_s",
        "_datasets": (train, test),
        "value": pipe_r["rows_per_s"], "unit": "rows/s",
        "quality": {
            "micro_batches": pipe_r["batches"],
            "latency_ms_p50": pipe_r["latency_ms_p50"],
            "latency_ms_p99": pipe_r["latency_ms_p99"],
            "pipeline": pipeline_evidence,
        },
        "n_rows": pipe_r["rows"],
    }


# config 6: whole-pipeline fusion, fused vs staged on the config-5-style
# CSV stream.  The serving pipeline is DEEPER than config 5's
# (assembler → MinMaxScaler → DCT → PCA → LR): the r5 scaler fold
# already collapses config 5's scaler→LR pair, so measuring fusion
# needs stages the fold cannot absorb — staged serving pays one device
# round trip per jitted feature stage (DCT, PCA) plus the head; fused
# serving runs ONE program with one upload and one download per batch.
BENCH6_PCA_K = 32
BENCH6_REPS = 5


def bench_config6(n_rows, mesh):
    """Fused vs staged serving throughput (rows/s) over a real file
    stream — the whole-pipeline fusion compiler (sntc_tpu/fuse/)
    measured, not asserted.  Methodology mirrors config 5: one synthetic
    stream served by both engines, reps interleaved, MEDIAN reported;
    additionally the host-serve crossover is pinned OFF for BOTH sides
    (both run the device predict path) and both use the same shape
    buckets, so the ratio isolates fusion — N programs + N−1 host hops
    vs one program.  The fused model's per-segment transfer counters,
    divided by the ENGINE's committed micro-batches, provide the
    uploads/downloads-per-batch evidence (must be exactly 1/1)."""
    import shutil
    import tempfile

    import pyarrow as pa

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.feature import DCT, MinMaxScaler, PCA
    from sntc_tpu.fuse import compile_pipeline, fused_segments
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
        MinMaxScaler(inputCol="rawFeatures", outputCol="mm"),
        DCT(inputCol="mm", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="features",
            k=BENCH6_PCA_K),
        LogisticRegression(mesh=mesh, maxIter=20),
    ]).fit(train)
    staged_model = PipelineModel(stages=pipe.getStages()[1:])
    fused_model = compile_pipeline(staged_model)
    segments = fused_segments(fused_model)

    def make_engine(tmp, name, in_dir, chunk_sizes, model):
        """Warm one engine's predictor (shared across all its reps):
        one throwaway engine batch for process-global first-touch
        costs, then every distinct chunk size straight through the
        predictor so bucketed shapes are all compiled."""
        predictor = BatchPredictor(model, bucket_rows=BENCH5_SHAPE_BUCKETS)
        warm = StreamingQuery(
            predictor, FileStreamSource(in_dir),
            CsvDirSink(os.path.join(tmp, f"warm_{name}"), durable=False),
            os.path.join(tmp, f"warmckpt_{name}"),
            max_batch_offsets=1, wal_mode="append",
        )
        warm._run_one_batch()
        warm.stop()
        for c in sorted(set(chunk_sizes)):
            predictor.predict_frame(test.slice(0, c))
        return {"name": name, "predictor": predictor, "reps": []}

    def run_once(tmp, eng, in_dir, rep, stream_rows, n_files):
        name = eng["name"]
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            eng["predictor"], FileStreamSource(in_dir),
            CsvDirSink(out_dir, durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=1, wal_mode="append",
            pipeline_depth=1,  # serial engines: the ratio is pure fusion
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        rows = (
            stream_rows
            if n_done == n_files
            else sum(p["numInputRows"] for p in q.recentProgress)
        )
        q.stop()
        eng["reps"].append({
            "out_dir": out_dir, "batches": n_done, "rows": rows,
            "dt": dt, "rows_per_s": rows / dt,
        })

    def median_rep(eng):
        reps = sorted(eng["reps"], key=lambda r: r["rows_per_s"])
        rec = dict(reps[len(reps) // 2])
        rec["best_rows_per_s"] = round(reps[-1]["rows_per_s"], 1)
        return rec

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # same intra-op pinning discipline as config 5
    host_rows_env = os.environ.get("SNTC_SERVE_HOST_ROWS")
    # crossover OFF for both engines: staged must run the same device
    # predict path the fused program embeds, or the ratio would compare
    # device serving against host serving instead of fused vs staged
    os.environ["SNTC_SERVE_HOST_ROWS"] = "0"
    try:
        in_dir = os.path.join(tmp, "in")
        chunk_sizes = _write_bench5_stream(
            in_dir, test, passes=BENCH5_STREAM_PASSES
        )
        stream_rows, n_files = sum(chunk_sizes), len(chunk_sizes)
        engines = [
            make_engine(tmp, "staged", in_dir, chunk_sizes, staged_model),
            make_engine(tmp, "fused", in_dir, chunk_sizes, fused_model),
        ]
        # warmup is done: snapshot the fused model's per-segment transfer
        # counters; the per-BATCH evidence divides the measured-window
        # deltas by the ENGINE's committed micro-batches, so a pipeline
        # broken into N segments would honestly report N per batch
        compiles_before = sum(s.compile_events for s in segments)
        uploads_before = sum(s.uploads for s in segments)
        downloads_before = sum(s.downloads for s in segments)
        for rep in range(BENCH6_REPS):
            for eng in engines:
                run_once(tmp, eng, in_dir, rep, stream_rows, n_files)
        staged, fused_r = (median_rep(e) for e in engines)
        fused_batches = sum(r["batches"] for r in engines[1]["reps"])
        uploads = sum(s.uploads for s in segments) - uploads_before
        downloads = sum(s.downloads for s in segments) - downloads_before
        sink_match = _sinks_match(
            _read_sink_dir(staged["out_dir"]),
            _read_sink_dir(fused_r["out_dir"]),
        )
    finally:
        pa.set_cpu_count(arrow_cpus)
        if host_rows_env is None:
            os.environ.pop("SNTC_SERVE_HOST_ROWS", None)
        else:
            os.environ["SNTC_SERVE_HOST_ROWS"] = host_rows_env
        shutil.rmtree(tmp, ignore_errors=True)
    fusion_evidence = {
        "speedup_vs_staged": _round_ratio(
            fused_r["rows_per_s"] / staged["rows_per_s"]
        ),
        "staged_rows_per_s": round(staged["rows_per_s"], 1),
        "best_rows_per_s": fused_r["best_rows_per_s"],
        "staged_best_rows_per_s": staged["best_rows_per_s"],
        "uploads_per_batch": round(uploads / max(fused_batches, 1), 3),
        "downloads_per_batch": round(
            downloads / max(fused_batches, 1), 3
        ),
        "fused_segments": len(segments),
        "fused_stages": sum(len(s.fused_stages) for s in segments),
        "compile_events": sum(s.compile_events for s in segments),
        "recompiles_after_warmup": sum(
            s.compile_events for s in segments
        ) - compiles_before,
        "fallbacks": sum(s.fallbacks for s in segments),
        "sink_match": sink_match,
        "reps": BENCH6_REPS,
        "batch_sizes": list(BENCH5_SIZES),
        "arrow_intra_op_threads": 1,
    }
    return {
        "metric": "cicids2017_fused_serving_rows_per_s",
        "_datasets": (train, test),
        "value": fused_r["rows_per_s"], "unit": "rows/s",
        "quality": {
            "micro_batches": fused_r["batches"],
            "fusion": fusion_evidence,
        },
        "n_rows": fused_r["rows"],
    }


# config 7: the live-model lifecycle arc (r11).  A two-day drifting
# stream is served end-to-end with the whole lifecycle armed — drift
# monitor, online partial_fit refit, shadow promotion, between-batches
# hot-swap — and the journaled evidence is the arc itself: the
# incumbent degrades after the shift, drift is detected N batches
# later, the refit candidate wins the gate and is promoted, macro-F1
# recovers, and the swap stalls zero batches.
BENCH7_BATCHES = 18
BENCH7_SHIFT_AT = 8
BENCH7_DRIFT_WINDOW = 3
BENCH7_DRIFT_THRESHOLD = 0.04
BENCH7_SHADOW_WINDOW = 4
BENCH7_CLASSES = 8


def bench_config7(n_rows, mesh):
    """Lifecycle-armed serving over the drifting stream: rows/s through
    the engine with drift detection + partial_fit + promotion running
    live (the r11 scenario measured end-to-end, one cold pass — the
    promotion protocol is one-shot per stream by design)."""
    import shutil
    import tempfile

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.data import (
        clean_flows,
        generate_drift_frames,
        write_drift_stream,
    )
    from sntc_tpu.feature import StringIndexer, VectorAssembler
    from sntc_tpu.lifecycle import (
        DriftMonitor,
        LifecycleManager,
        ModelPromoter,
        macro_f1,
    )
    from sntc_tpu.mlio import save_model
    from sntc_tpu.models import NaiveBayes
    from sntc_tpu.serve import CsvDirSink, FileStreamSource, StreamingQuery

    rows_per_batch = max(256, n_rows // BENCH7_BATCHES)
    gen_kwargs = dict(
        rows_per_batch=rows_per_batch, shift_at=BENCH7_SHIFT_AT,
        seed=SEED, n_classes=BENCH7_CLASSES,
    )
    frames = generate_drift_frames(BENCH7_BATCHES, **gen_kwargs)
    train = clean_flows(Frame.concat_all(frames[:BENCH7_SHIFT_AT]))
    feat_cols = [c for c in train.columns if c != "Label"]
    fitted = Pipeline(stages=[
        StringIndexer(inputCol="Label", outputCol="label"),
        VectorAssembler(inputCols=feat_cols, outputCol="features"),
        NaiveBayes(mesh=mesh, modelType="gaussian"),
    ]).fit(train)
    labels = fitted.getStages()[0].labels
    # serve form: the label indexer comes off (live flows carry no
    # label for the MODEL; the lifecycle reads the stream's Label
    # column directly through the promoter's label mapping)
    serving = PipelineModel(stages=fitted.getStages()[1:])
    label_index = {str(v): i for i, v in enumerate(labels)}

    tmp = tempfile.mkdtemp()
    try:
        in_dir = os.path.join(tmp, "in")
        write_drift_stream(in_dir, BENCH7_BATCHES, frames=frames)
        serving_path = os.path.join(tmp, "model")
        ckpt = os.path.join(tmp, "ckpt")
        save_model(serving, serving_path)
        drift = DriftMonitor(
            window=BENCH7_DRIFT_WINDOW,
            threshold=BENCH7_DRIFT_THRESHOLD,
        ).attach()
        promoter = ModelPromoter(
            serving, incumbent_raw=serving, serving_path=serving_path,
            checkpoint_dir=ckpt, window=BENCH7_SHADOW_WINDOW,
            # a real win, not refit jitter, gates promotion — without a
            # margin the online refit re-promotes itself every window
            margin=0.05,
            label_col="Label", labels=labels, probation_batches=2,
        )
        mgr = LifecycleManager(
            drift=drift, promoter=promoter,
            n_classes=BENCH7_CLASSES,
        )

        # the ops arc, event-driven: serve normally until the monitor
        # raises drift_detected, THEN start refitting a candidate from
        # the live labeled batches — so the promotion that follows is
        # the RESPONSE to the detected shift, not refit churn (which
        # would also keep resetting the drift baseline via its swaps).
        # The event record is also the durable detection evidence: the
        # monitor's own stats reset when the promotion swap lands.
        drift_event = {}

        def _arm_refit_on_drift(rec):
            if rec.get("event") == "drift_detected" and not drift_event:
                drift_event.update(rec)
                mgr.partial_fit = True

        from sntc_tpu.resilience import (
            add_event_observer,
            remove_event_observer,
        )

        add_event_observer(_arm_refit_on_drift)
        out_dir = os.path.join(tmp, "out")
        q = StreamingQuery(
            serving, FileStreamSource(in_dir),
            CsvDirSink(out_dir, columns=["prediction"], durable=False),
            ckpt, max_batch_offsets=1, lifecycle=mgr,
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        stream_rows = BENCH7_BATCHES * rows_per_batch
        stats = q.pipeline_stats()
        lc = stats["lifecycle"]
        remove_event_observer(_arm_refit_on_drift)
        drift.detach()
        q.stop()

        # the macro-F1 arc, batch by batch, from the sink against the
        # stream's own labels (batch i == part_i — the fixture is
        # deterministic)
        import pyarrow.csv as pacsv

        f1_by_batch = []
        for i, f in enumerate(frames):
            t = pacsv.read_csv(
                os.path.join(out_dir, f"batch_{i:06d}.csv")
            )
            y = np.asarray(
                [label_index.get(str(v), -1) for v in f["Label"]],
                np.int64,
            )
            pred = t.column("prediction").to_numpy()
            known = y >= 0
            f1_by_batch.append(
                round(macro_f1(y[known], pred[known]), 4)
            )
        shift = BENCH7_SHIFT_AT
        detected = drift_event.get("batch_id")
        promoted_at = None
        promo_journal = os.path.join(ckpt, "promotion.jsonl")
        if os.path.exists(promo_journal):
            with open(promo_journal) as jf:
                for line in jf:
                    rec = json.loads(line)
                    if (
                        rec.get("action") == "shadow_score"
                        and rec.get("decision") == "promote"
                    ):
                        promoted_at = rec["batch_id"]
                        break
        arc = {
            "f1_pre_shift": round(
                float(np.mean(f1_by_batch[:shift])), 4
            ),
            "f1_post_shift_degraded": f1_by_batch[shift],
            "f1_recovered": round(
                float(np.mean(f1_by_batch[-2:])), 4
            ),
            "f1_by_batch": f1_by_batch,
        }
        evidence = {
            "batches": n_done,
            # swap downtime: every stream batch committed in one pass —
            # the between-batches swap stalls NOTHING (contract: 0)
            "batches_stalled": BENCH7_BATCHES
            - stats["delivered_batches"],
            "shift_at_batch": shift,
            "drift_detected": bool(drift_event),
            "drift_detected_batch": detected,
            "drift_divergence": drift_event.get("divergence"),
            "detection_latency_batches": (
                detected - shift if detected is not None else None
            ),
            "promoted_at_batch": promoted_at,
            "partial_fit_batches": lc["partial_fit_batches"],
            "promotions": lc["promoter"]["promotions"],
            "rollbacks": lc["promoter"]["rollbacks"],
            "models_swapped": lc["models_swapped"],
            "generation": lc["promoter"]["generation"],
            "shadow_window": BENCH7_SHADOW_WINDOW,
            "drift_window": BENCH7_DRIFT_WINDOW,
            "drift_threshold": BENCH7_DRIFT_THRESHOLD,
            "rows_per_batch": rows_per_batch,
            "arc": arc,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "cicids2017_lifecycle_arc_rows_per_s",
        "_datasets": (train, frames),
        "value": stream_rows / dt,
        "unit": "rows/s",
        "quality": {"lifecycle": evidence},
        "n_rows": stream_rows,
    }


# config 8: the multi-tenant serve front door (r12).  10 well-behaved
# tenant streams (8 sharing an LR pipeline, 2 sharing a gaussian-NB
# pipeline) run through one ServeDaemon over SHARED BatchPredictors,
# in three phases: (S) single-tenant device throughput — plain
# StreamingQuery per pipeline over the same total rows, the
# no-multiplexing ceiling; (A) the clean 10-tenant daemon — aggregate
# rows/s (the headline, acceptance >= 0.8x single) plus per-tenant
# p50/p99 and the shared-predictor compile ledger (cross-tenant
# recompiles after warmup == 0); (B) the same 10 plus a NOISY tenant —
# a 3x flooding stream with corrupt files under a strict row policy —
# which must end QUARANTINED by its own strikes (shed + dead-letter
# journaled under its own namespace) while the well-behaved tenants'
# p99 stays within 2x their phase-A baseline and the daemon itself
# never crashes.
BENCH8_TENANTS = 10
BENCH8_LR_TENANTS = 8  # the other 2 share the NB pipeline
BENCH8_SIZES = (1024, 512, 256)  # per-tenant micro-batch row cycle
BENCH8_SHAPE_BUCKETS = 256
BENCH8_NOISY_PASSES = 3  # the flood: noisy stream is 3x a tenant's
BENCH8_NOISY_CORRUPT_EVERY = 3  # every 3rd noisy file is poison


def _bench8_corrupt(in_dir, every):
    """Deterministically poison every ``every``-th part file with a
    ragged tail line (wrong field count -> the strict parser fails the
    batch); returns the poisoned file count."""
    files = sorted(glob.glob(os.path.join(in_dir, "part_*.csv")))
    poisoned = 0
    for i, path in enumerate(files):
        if i % every:
            continue
        with open(path, "a") as f:
            f.write("garbage,not,a,flow,row\n")
        poisoned += 1
    return poisoned


def bench_config8(n_rows, mesh):
    """Multi-tenant serving: aggregate rows/s through the ServeDaemon
    with 10+ concurrent tenant streams on shared compiled programs —
    fair scheduling, per-tenant isolation, and the noisy-neighbor
    chaos arc measured end-to-end (docs/RESILIENCE.md "Multi-tenant
    serving")."""
    import shutil
    import tempfile

    import pyarrow as pa

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.models import LogisticRegression, NaiveBayes
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        ServeDaemon,
        StreamingQuery,
        TenantSpec,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    lr_model = compile_serving(PipelineModel(stages=Pipeline(
        stages=_feature_stages(mesh) + [
            LogisticRegression(mesh=mesh, maxIter=20)
        ]
    ).fit(train).getStages()[1:]))
    nb_model = compile_serving(PipelineModel(stages=Pipeline(
        stages=_feature_stages(mesh) + [
            NaiveBayes(mesh=mesh, modelType="gaussian")
        ]
    ).fit(train).getStages()[1:]))
    # ONE predictor per pipeline signature, shared by every tenant of
    # that pipeline across all three phases — the shared program cache
    # whose ledger is the zero-cross-tenant-recompiles evidence
    lr_pred = BatchPredictor(lr_model, bucket_rows=BENCH8_SHAPE_BUCKETS)
    nb_pred = BatchPredictor(nb_model, bucket_rows=BENCH8_SHAPE_BUCKETS)

    well_behaved = [
        (f"lr{i:02d}", lr_pred) for i in range(BENCH8_LR_TENANTS)
    ] + [
        (f"nb{i:02d}", nb_pred)
        for i in range(BENCH8_TENANTS - BENCH8_LR_TENANTS)
    ]

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # config-5 intra-op pinning discipline
    try:
        # per-tenant streams (identical row content, own directories);
        # plus one combined dir per pipeline for the single-tenant
        # baseline (hardlinked — same bytes, no copy)
        tenant_files = {}
        for tid, _pred in well_behaved:
            tenant_files[tid] = _write_bench5_stream(
                os.path.join(tmp, "in", tid), test,
                chunk_cycle=BENCH8_SIZES,
            )
        for pipe_name, members in (
            ("lr", [t for t, p in well_behaved if p is lr_pred]),
            ("nb", [t for t, p in well_behaved if p is nb_pred]),
        ):
            combined = os.path.join(tmp, "in", f"single_{pipe_name}")
            os.makedirs(combined, exist_ok=True)
            n = 0
            for tid in members:
                for src in sorted(glob.glob(
                    os.path.join(tmp, "in", tid, "part_*.csv")
                )):
                    os.link(
                        src,
                        os.path.join(combined, f"part_{n:05d}.csv"),
                    )
                    n += 1
        noisy_files = _write_bench5_stream(
            os.path.join(tmp, "in", "noisy"), test,
            passes=BENCH8_NOISY_PASSES, chunk_cycle=BENCH8_SIZES,
        )
        poisoned = _bench8_corrupt(
            os.path.join(tmp, "in", "noisy"),
            BENCH8_NOISY_CORRUPT_EVERY,
        )

        # warm every distinct chunk shape through BOTH shared
        # predictors once; everything after this is the measured cache
        for pred in (lr_pred, nb_pred):
            for c in sorted(set(sum(tenant_files.values(), [])
                                + noisy_files)):
                pred.predict_frame(test.slice(0, c))
        compiles_warm = lr_pred.compile_events + nb_pred.compile_events

        def _spec(tid, pred, watch, phase, **kw):
            # explicit sink so durable=False matches the phase-S
            # baseline engines (fsync-per-batch would bill the daemon
            # for durability the ceiling measurement doesn't pay)
            return TenantSpec(
                tenant_id=tid, model=pred, watch=watch,
                sink=CsvDirSink(
                    os.path.join(tmp, "out", phase, tid),
                    columns=["prediction"], durable=False,
                ),
                max_batch_offsets=1, max_batch_failures=2, **kw,
            )

        def _run_daemon(phase, with_noisy):
            specs = [
                _spec(tid, pred, os.path.join(tmp, "in", tid), phase)
                for tid, pred in well_behaved
            ]
            if with_noisy:
                # backlog cap well below the flood (most of it sheds)
                # but wide enough that several poison files survive the
                # shed and strike: the ladder must act on evidence, not
                # on the shedder having hidden it
                specs.append(_spec(
                    "noisy", lr_pred, os.path.join(tmp, "in", "noisy"),
                    phase, max_pending_batches=16, shed_policy="oldest",
                    quarantine_after=3, stop_after=99,
                    quarantine_cooldown_s=1e9,
                ))
            daemon = ServeDaemon(
                specs, os.path.join(tmp, f"root_{phase}"),
                shape_buckets=BENCH8_SHAPE_BUCKETS,
            )
            try:
                t0 = time.perf_counter()
                daemon.process_available()
                dt = time.perf_counter() - t0
                snap = {
                    t.spec.tenant_id: t.snapshot() for t in daemon.tenants
                }
                rows = sum(
                    s["rows_done"] for tid, s in snap.items()
                    if tid != "noisy"
                )
                return {
                    "dt": dt, "rows": rows, "tenants": snap,
                    "status": daemon.status(),
                }
            finally:
                daemon.close()

        # phase S: the no-multiplexing ceiling — one plain engine per
        # pipeline over the SAME total rows on the same warm
        # predictors.  Row count comes from the stream writer's exact
        # ledger (recentProgress is a bounded ring), and the combined
        # dirs hold every tenant's files exactly once.
        single_dt = 0.0
        for pipe_name, pred in (("lr", lr_pred), ("nb", nb_pred)):
            src = FileStreamSource(
                os.path.join(tmp, "in", f"single_{pipe_name}")
            )
            q = StreamingQuery(
                pred, src,
                CsvDirSink(os.path.join(tmp, f"out_single_{pipe_name}"),
                           columns=["prediction"], durable=False),
                os.path.join(tmp, f"ckpt_single_{pipe_name}"),
                max_batch_offsets=1, wal_mode="append",
            )
            t0 = time.perf_counter()
            q.process_available()
            single_dt += time.perf_counter() - t0
            q.stop()
            src.close()
        single_rows = sum(sum(v) for v in tenant_files.values())
        single_rows_per_s = single_rows / single_dt

        clean = _run_daemon("clean", with_noisy=False)
        noisy = _run_daemon("noisy", with_noisy=True)
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)

    compiles_after = lr_pred.compile_events + nb_pred.compile_events
    agg_rows_per_s = clean["rows"] / clean["dt"]
    p99_base = {
        tid: s["p99_ms"] for tid, s in clean["tenants"].items()
    }
    p99_noisy = {
        tid: s["p99_ms"] for tid, s in noisy["tenants"].items()
        if tid != "noisy"
    }
    # None-safe: a tenant that committed nothing in a phase has no
    # percentiles; journal a degraded ratio rather than dying after
    # all three phases' work
    ratios = [
        p99_noisy[tid] / p99_base[tid]
        for tid in p99_noisy
        if p99_base.get(tid) and p99_noisy[tid] is not None
    ]
    p99_ratio_worst = max(ratios) if ratios else None
    noisy_row = noisy["tenants"]["noisy"]
    evidence = {
        "tenants": BENCH8_TENANTS,
        "pipelines": {"lr": BENCH8_LR_TENANTS,
                      "nb": BENCH8_TENANTS - BENCH8_LR_TENANTS},
        "shape_buckets": BENCH8_SHAPE_BUCKETS,
        "aggregate_rows_per_s": round(agg_rows_per_s, 1),
        "single_tenant_rows_per_s": round(single_rows_per_s, 1),
        "aggregate_vs_single": _round_ratio(
            agg_rows_per_s / single_rows_per_s
        ),
        "recompiles_after_warmup": compiles_after - compiles_warm,
        "latency_ms_p50_median": round(float(np.median(
            [s["p50_ms"] for s in clean["tenants"].values()
             if s["p50_ms"] is not None] or [np.nan]
        )), 3),
        "latency_ms_p99_max": round(
            max([v for v in p99_base.values() if v is not None],
                default=float("nan")), 3
        ),
        "noisy_neighbor": {
            "state": noisy_row["state"],
            "flood_passes": BENCH8_NOISY_PASSES,
            "poisoned_files": poisoned,
            "quarantine_episodes": noisy_row["quarantine_episodes"],
            "shed_total_offsets": noisy_row["shed_total_offsets"],
            "daemon_survived": True,  # _run_daemon returned, not raised
            "well_behaved_p99_ratio_worst": (
                None if p99_ratio_worst is None
                else _round_ratio(p99_ratio_worst)
            ),
            "events_dropped_by_tenant": noisy["status"][
                "events_dropped_by_tenant"
            ],
        },
    }
    return {
        "metric": "cicids2017_multi_tenant_serving_rows_per_s",
        "_datasets": (train, test),
        "value": agg_rows_per_s,
        "unit": "rows/s",
        "quality": {"tenancy": evidence},
        "n_rows": clean["rows"],
    }


# config 9: the stateful flow-feature engine (r14).  A synthetic raw
# pcap capture stream (deterministic flows spanning file boundaries +
# an out-of-order tail) is served end-to-end — parse → keyed session
# windows → CICIDS2017 feature rows → classify — and compared against
# the precomputed-CSV path serving the SAME feature rows through the
# same predictor: the cost of computing the features live, measured.
# The CSV stream is written from the capture path's own reference
# emissions, so row parity is by construction and the two sinks'
# prediction sequences must match row-for-row.
BENCH9_PACKETS_PER_FLOW = 6
BENCH9_FLOWS_PER_FILE = 256
BENCH9_SHAPE_BUCKETS = 256
BENCH9_REPS = 3
BENCH9_FLOW_TIMEOUT = 5.0
# lateness > the inter-file gap: the deferred (out-of-order) tail is
# ACCEPTED and reordered into its windows rather than dropped late —
# the representative ISP-capture shape; the late-drop path is pinned
# by tests, not the bench
BENCH9_LATENESS = 35.0
BENCH9_FILE_GAP_S = 30.0


def bench_config9(n_rows, mesh):
    """Raw-capture flow serving throughput: replayed capture →
    windowed features → classify rows/s vs the precomputed-CSV path on
    the same rows (docs/RESILIENCE.md "Stateful flow windows").  The
    journal record's ``obs`` delta carries the ``sntc_flow_*``
    state/eviction series as the operator evidence."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.data.synth import write_capture_stream
    from sntc_tpu.flow import FlowCaptureSource
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)
    serve_model = compile_serving(
        PipelineModel(stages=pipe.getStages()[1:])
    )
    # ONE predictor across both paths and every rep: the compile
    # ledger is shared, so the ratio isolates feature computation
    predictor = BatchPredictor(
        serve_model, bucket_rows=BENCH9_SHAPE_BUCKETS
    )
    n_flows = max(64, n_rows // 4)
    n_files = max(2, n_flows // BENCH9_FLOWS_PER_FILE)

    def flow_source(tmp, rep, state=True):
        # the commit-less reference pass runs store-less: with no
        # commits to prune them, staged snapshots would only pile up
        return FlowCaptureSource(
            os.path.join(tmp, "in_cap"), format="pcap",
            flow_timeout=BENCH9_FLOW_TIMEOUT,
            allowed_lateness=BENCH9_LATENESS,
            state_dir=(
                os.path.join(tmp, f"ckpt_cap_{rep}", "flow_state")
                if state else None
            ),
        )

    def timed_pass(tmp, name, rep, source):
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            predictor, source,
            CsvDirSink(out_dir, columns=["prediction"], durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            # SAME WAL mode on both sides: the ratio must isolate
            # feature computation, not a WAL-format delta
            max_batch_offsets=1, wal_mode="append",
        )
        t0 = time.perf_counter()
        q.process_available()
        dt = time.perf_counter() - t0
        q.stop()
        close = getattr(source, "close", None)
        if close is not None:
            close()
        return dt, out_dir, source

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # config-5 intra-op pinning discipline
    try:
        cap_info = write_capture_stream(
            os.path.join(tmp, "in_cap"),
            n_files=n_files,
            flows_per_file=max(1, n_flows // n_files),
            packets_per_flow=BENCH9_PACKETS_PER_FLOW,
            seed=SEED, file_gap_s=BENCH9_FILE_GAP_S,
            defer_fraction=0.1, flush=True,
        )
        n_packets = int(cap_info["packets"].shape[0])
        # reference pass: drive the source directly to (a) capture the
        # emitted feature frames the CSV path will serve and (b) warm
        # every bucket shape through the shared predictor — untimed
        ref_src = flow_source(tmp, "ref", state=False)
        emitted = []
        for i in range(ref_src.latest_offset()):
            f = ref_src.get_batch(i, i + 1)
            if f.num_rows:
                emitted.append(f)
                predictor.predict_frame(f)
        feature_rows = sum(f.num_rows for f in emitted)
        csv_dir = os.path.join(tmp, "in_csv")
        os.makedirs(csv_dir, exist_ok=True)
        for k, f in enumerate(emitted):
            pacsv.write_csv(
                f.select(CICIDS2017_FEATURES).to_arrow(),
                os.path.join(csv_dir, f"part_{k:05d}.csv"),
            )
        ref_stats = ref_src.flow_stats()
        ref_src.close()
        # one untimed CSV warmup pass (pyarrow pools, WAL/sink paths)
        timed_pass(tmp, "csvwarm", 0,
                   FileStreamSource(csv_dir))
        reps = {"cap": [], "csv": []}
        flow_stats = None
        for rep in range(BENCH9_REPS):
            # interleave the two paths (config-5 host-drift hygiene)
            dt, out_cap, src = timed_pass(
                tmp, "cap", rep, flow_source(tmp, rep)
            )
            reps["cap"].append((dt, out_cap))
            flow_stats = src.flow_stats()
            dt, out_csv, _ = timed_pass(
                tmp, "csv", rep, FileStreamSource(csv_dir)
            )
            reps["csv"].append((dt, out_csv))
        med = {
            k: sorted(v)[len(v) // 2] for k, v in reps.items()
        }
        # the config-5/6 sink-parity check: full row-for-row equality
        # of the two paths' concatenated sink output
        sink_match = _sinks_match(
            _read_sink_dir(med["cap"][1]),
            _read_sink_dir(med["csv"][1]),
        )
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)
    cap_rows_per_s = feature_rows / med["cap"][0]
    csv_rows_per_s = feature_rows / med["csv"][0]
    evidence = {
        "capture_files": n_files + 1,  # + the flush sentinel file
        "packets": n_packets,
        "flows": cap_info["n_flows"],
        "feature_rows": feature_rows,
        "packets_per_s": round(n_packets / med["cap"][0], 1),
        "csv_rows_per_s": round(csv_rows_per_s, 1),
        "capture_vs_csv": _round_ratio(cap_rows_per_s / csv_rows_per_s),
        "sink_match": sink_match,
        "shape_buckets": BENCH9_SHAPE_BUCKETS,
        "reps": BENCH9_REPS,
        "windows_emitted": ref_stats["windows_emitted"],
        "out_of_order": ref_stats["out_of_order"],
        "late_records": ref_stats["late_records"],
        "evictions": ref_stats["evictions"],
        "snapshots_published": flow_stats["snapshots_published"],
        "state_packets_final": flow_stats["packets"],
    }
    return {
        "metric": "cicids2017_capture_flow_serving_rows_per_s",
        "_datasets": (train, test),
        "value": cap_rows_per_s,
        "unit": "rows/s",
        "quality": {"flow": evidence},
        "n_rows": feature_rows,
    }


# config 10: the autotuned zero-copy ingest engine (r15).  The
# config-5/6 rows/s-at-saturation harness, asked a different question:
# can a COLD-DEFAULT engine (read_workers=1, prefetch=1) with the
# ingest autotuner armed find — or beat — the best hand-tuned
# (--read-workers, --prefetch-batches) combination on its own?  All
# engines (grid and autotuned) parse through the zero-copy columnar
# plane (FileStreamSource(columnar=True): one in-Arrow f32 cast at
# parse, numpy views to the fused program's single upload), micro-
# batches cover 2 files so the read-worker knob is real, and the
# journal carries the full grid, the tuner's applied-decision journal
# + final knobs, the per-stage meter snapshots, the transfer-ledger
# uploads-per-batch (must stay exactly 1 through the fused program),
# and the loader-bitwise / sink-parity proofs.
BENCH10_GRID = ((1, 1), (1, 4), (4, 1), (4, 4))  # (read_workers, prefetch)
BENCH10_REPS = 3
BENCH10_FILES_PER_BATCH = 2


def bench_config10(n_rows, mesh):
    """Autotuned ingest vs the hand-tuned flag grid (docstring above;
    docs/PERFORMANCE.md "Autotuned ingest" has the methodology)."""
    import shutil
    import tempfile

    import pyarrow as pa

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.data.autotune import AutotunePolicy, IngestAutotuner
    from sntc_tpu.data.ingest import clean_flows, load_csv
    from sntc_tpu.data.pipeline import read_flows_columnar
    from sntc_tpu.feature import DCT, MinMaxScaler, PCA
    from sntc_tpu.fuse import compile_pipeline, fused_segments
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    train, test = _dataset(n_rows, binary=True)
    # the config-6 serving pipeline: deep enough that the scaler fold
    # cannot absorb it, so the served model is a real FusedSegment
    # program — ONE upload + ONE download per batch is then a claim
    # the engine's transfer ledger can actually prove
    pipe = Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
        MinMaxScaler(inputCol="rawFeatures", outputCol="mm"),
        DCT(inputCol="mm", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="features",
            k=BENCH6_PCA_K),
        LogisticRegression(mesh=mesh, maxIter=20),
    ]).fit(train)
    serve_model = compile_pipeline(
        PipelineModel(stages=pipe.getStages()[1:])
    )
    n_segments = len(fused_segments(serve_model))

    def run_once(tmp, name, rep, source, predictor, stream_rows,
                 n_files, autotuner=None):
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            predictor, source,
            CsvDirSink(out_dir, durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=BENCH10_FILES_PER_BATCH,
            wal_mode="append",
            pipeline_depth=2, overlap_sink=True,
            autotuner=autotuner,
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        rows = (
            stream_rows
            if n_done * BENCH10_FILES_PER_BATCH >= n_files
            else sum(p["numInputRows"] for p in q.recentProgress)
        )
        stats = q.pipeline_stats()
        q.stop()
        return {
            "out_dir": out_dir, "batches": n_done, "rows": rows,
            "dt": dt, "rows_per_s": rows / dt, "stats": stats,
        }

    def median(reps):
        return sorted(reps, key=lambda r: r["rows_per_s"])[len(reps) // 2]

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # intra-op pinning, config-5 discipline
    host_rows_env = os.environ.get("SNTC_SERVE_HOST_ROWS")
    # crossover OFF (config-6 discipline): every batch runs the fused
    # DEVICE path, so the transfer ledger's uploads-per-batch is the
    # real zero-copy evidence rather than an empty host-path ledger
    os.environ["SNTC_SERVE_HOST_ROWS"] = "0"
    try:
        in_dir = os.path.join(tmp, "in")
        chunk_sizes = _write_bench5_stream(
            in_dir, test, passes=BENCH5_STREAM_PASSES
        )
        stream_rows, n_files = sum(chunk_sizes), len(chunk_sizes)
        # ONE predictor for every run (grid + autotuned): compile_events
        # is a single ledger, recompiles_after_warmup must stay 0
        predictor = BatchPredictor(
            serve_model, bucket_rows=BENCH5_SHAPE_BUCKETS
        )
        warm_sizes = set(chunk_sizes) | {
            sum(s) for s in zip(chunk_sizes[::2], chunk_sizes[1::2])
        }
        for c in sorted(warm_sizes):
            predictor.predict_frame(test.slice(0, c))
        compiles_warm = predictor.compile_events
        # the loader-bitwise proof: legacy load_csv+clean_flows vs the
        # zero-copy columnar loader, on a raw (dirty) day CSV
        from sntc_tpu.data import write_day_csvs

        dirty_dir = os.path.join(tmp, "dirty")
        dirty_csv = write_day_csvs(
            dirty_dir, n_rows_per_day=4000, n_days=1, seed=7
        )[0]
        legacy = clean_flows(load_csv(dirty_csv))
        columnar = read_flows_columnar(dirty_csv, handle_invalid="drop")
        zero_copy_bitwise = (
            legacy.columns == columnar.columns
            and legacy.num_rows == columnar.num_rows
            and all(
                np.array_equal(legacy[c], columnar[c])
                for c in legacy.columns
            )
        )
        # autotuned engine: ONE cold-default source + ONE tuner shared
        # across reps (knobs live on the source, so converged settings
        # persist — rows/s AT SATURATION); one unmeasured convergence
        # pass first, exactly like every engine's compile warmup
        auto_src = FileStreamSource(
            in_dir, columnar=True, read_workers=1, prefetch_batches=1
        )
        tuner = IngestAutotuner(
            policy=AutotunePolicy(interval_ticks=2, confirm=2,
                                  cooldown=1)
        )
        run_once(tmp, "auto_warm", 0, auto_src, predictor, stream_rows,
                 n_files, autotuner=tuner)
        grid_reps = {combo: [] for combo in BENCH10_GRID}
        auto_reps = []
        for rep in range(BENCH10_REPS):
            for rw, pf in BENCH10_GRID:
                src = FileStreamSource(
                    in_dir, columnar=True,
                    read_workers=rw, prefetch_batches=pf,
                )
                grid_reps[(rw, pf)].append(run_once(
                    tmp, f"grid_{rw}_{pf}", rep, src, predictor,
                    stream_rows, n_files,
                ))
                src.close()
            auto_reps.append(run_once(
                tmp, "auto", rep, auto_src, predictor, stream_rows,
                n_files, autotuner=tuner,
            ))
        auto_src.close()
        grid_med = {
            combo: median(reps) for combo, reps in grid_reps.items()
        }
        best_combo = max(
            grid_med, key=lambda c: grid_med[c]["rows_per_s"]
        )
        best = grid_med[best_combo]
        auto = median(auto_reps)
        sink_match = _sinks_match(
            _read_sink_dir(best["out_dir"]),
            _read_sink_dir(auto["out_dir"]),
        )
        transfers = auto["stats"]["transfers"]
        uploads_per_batch = transfers["uploads"] / max(
            1, auto["batches"]
        )
        recompiles = predictor.compile_events - compiles_warm
    finally:
        pa.set_cpu_count(arrow_cpus)
        if host_rows_env is None:
            os.environ.pop("SNTC_SERVE_HOST_ROWS", None)
        else:
            os.environ["SNTC_SERVE_HOST_ROWS"] = host_rows_env
        shutil.rmtree(tmp, ignore_errors=True)
    autotune_evidence = {
        "grid": {
            f"rw{rw}_pf{pf}": round(grid_med[(rw, pf)]["rows_per_s"], 1)
            for rw, pf in BENCH10_GRID
        },
        "best_hand_tuned": {
            "read_workers": best_combo[0],
            "prefetch_batches": best_combo[1],
            "rows_per_s": round(best["rows_per_s"], 1),
        },
        "autotuned_rows_per_s": round(auto["rows_per_s"], 1),
        "autotune_vs_best_hand_tuned": _round_ratio(
            auto["rows_per_s"] / best["rows_per_s"]
        ),
        "final_knobs": auto["stats"]["autotune"]["knobs"],
        "decisions_applied": auto["stats"]["autotune"]["applied"],
        "decision_journal": [
            {k: d[k] for k in ("action", "knob", "direction", "from",
                               "to", "window")}
            for d in tuner.decisions
        ],
        "stage_latency": {
            stage: m for stage, m in auto["stats"]["ingest"].items()
        },
        "prefetch": auto["stats"].get("prefetch"),
        "uploads_per_batch": round(uploads_per_batch, 3),
        "fused_segments": n_segments,
        "recompiles_after_warmup": recompiles,
        "zero_copy_bitwise": zero_copy_bitwise,
        "sink_match": sink_match,
        "columnar_parse": True,
        "files_per_batch": BENCH10_FILES_PER_BATCH,
        "reps": BENCH10_REPS,
        "arrow_intra_op_threads": 1,
    }
    return {
        "metric": "cicids2017_autotuned_ingest_rows_per_s",
        "_datasets": (train, test),
        "value": auto["rows_per_s"], "unit": "rows/s",
        "quality": {
            "micro_batches": auto["batches"],
            "autotune": autotune_evidence,
        },
        "n_rows": auto["rows"],
    }


# config 11: the closed-loop SLO controller (r16).  The question: can
# COLD defaults + the controller recover the throughput the hand-tuned
# flag sets of earlier PRs bought, with nobody setting a flag?  Two
# arms, each an interleaved hand-vs-controller comparison on one
# stream state:
#   (A) single-stream — the config-5 pipelined flag set
#       (shape_buckets=256, pipeline_depth=3, prefetch=2) vs COLD
#       DEFAULTS (the serve CLI's untuned out-of-the-box values:
#       depth 2, prefetch 2, 4 read workers, no buckets) + the
#       controller steering depth and delegating the ingest knobs
#       toward a declared throughput SLO — its learning curve runs
#       INSIDE the measured window (the honest cold-start number);
#   (B) daemon — the config-8 flag set (shape_buckets=256 over 10
#       shared-predictor tenants) vs cold defaults + the controller
#       armed through ServeDaemon(controller=True), under ACHIEVABLE
#       declared SLOs (p99 + throughput floor): the controller's job
#       on a compliant plane is to hold it steady, not to destabilize
#       it chasing an impossible setpoint (per-batch latency INCLUDES
#       pipeline queue wait, so blindly deepening pipelines under a
#       10-tenant rotation trades p99 for nothing — the smoke journal
#       for this config shows exactly that arc when the floor is
#       declared unreachable).
# Acceptance: controller/hand-tuned rows/s >= 0.95 on both arms,
# worst well-behaved p99 ratio < 2 (arm B), the full decision
# journal, final knob values, and per-tenant SLO compliance in the
# JSON line.
BENCH11_TENANTS = 10
BENCH11_LR_TENANTS = 8
BENCH11_SIZES = (1024, 512, 256)
BENCH11_REPS = 3
# arm A runs LONGER than config 5 (4 stream passes) so the
# controller's cold learning curve is amortized the way a real
# long-lived stream amortizes it, and BOTH arms run the SAME
# supervisor-tick serving loop so loop overhead cancels out of the
# ratio (the controller samples every 4th tick)
BENCH11_STREAM_PASSES = 4
BENCH11_CTL_INTERVAL = 4


def bench_config11(n_rows, mesh):
    """Self-driving serve plane: cold defaults + ServeController vs
    the hand-tuned config-5 / config-8 flag sets (docs/RESILIENCE.md
    "Closed-loop SLO control")."""
    import shutil
    import tempfile

    import pyarrow as pa

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.models import LogisticRegression, NaiveBayes
    from sntc_tpu.resilience import QuerySupervisor
    from sntc_tpu.resilience.control import ControlPolicy
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        ServeDaemon,
        SloPolicy,
        StreamingQuery,
        TenantSpec,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    lr_model = compile_serving(PipelineModel(stages=Pipeline(
        stages=_feature_stages(mesh) + [
            LogisticRegression(mesh=mesh, maxIter=20)
        ]
    ).fit(train).getStages()[1:]))
    nb_model = compile_serving(PipelineModel(stages=Pipeline(
        stages=_feature_stages(mesh) + [
            NaiveBayes(mesh=mesh, modelType="gaussian")
        ]
    ).fit(train).getStages()[1:]))
    ctl_policy = ControlPolicy(confirm=1, cooldown=0)

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # config-5 intra-op pinning discipline
    try:
        # ---- arm A: single stream, config-5 flag set vs cold+ctl ----
        in_single = os.path.join(tmp, "in_single")
        sizes = _write_bench5_stream(
            in_single, test, passes=BENCH11_STREAM_PASSES
        )
        stream_rows, n_files = sum(sizes), len(sizes)
        hand_pred = BatchPredictor(
            lr_model, bucket_rows=BENCH5_SHAPE_BUCKETS
        )
        cold_pred = BatchPredictor(lr_model, bucket_rows=0)
        # warm both predictors on every distinct chunk shape (and the
        # process-global first-touch costs) outside the timed windows
        warm = StreamingQuery(
            hand_pred, FileStreamSource(in_single),
            CsvDirSink(os.path.join(tmp, "warm"), durable=False),
            os.path.join(tmp, "warmckpt"),
            max_batch_offsets=1, wal_mode="append",
        )
        warm._run_one_batch()
        warm.stop()
        for c in sorted(set(sizes)):
            hand_pred.predict_frame(test.slice(0, c))
            cold_pred.predict_frame(test.slice(0, c))

        def _drive(sup, q):
            """The ONE serving loop both arms share (supervisor-tick
            cadence, the `serve` CLI's supervised loop): loop
            overhead cancels out of the arm ratio."""
            t0 = time.perf_counter()
            stalled = 0
            while stalled < 8:
                if sup.tick() == 0 and not (
                    q.in_flight_count() or q.backlog_offsets()
                ):
                    stalled += 1
                else:
                    stalled = 0
            return time.perf_counter() - t0

        def run_hand(rep):
            src = FileStreamSource(
                in_single, prefetch_batches=BENCH5_PREFETCH,
            )
            q = StreamingQuery(
                hand_pred, src,
                CsvDirSink(os.path.join(tmp, f"out_h{rep}"),
                           durable=False),
                os.path.join(tmp, f"ckpt_h{rep}"),
                max_batch_offsets=1, wal_mode="append",
                pipeline_depth=BENCH5_PIPELINE_DEPTH,
                overlap_sink=True,
            )
            sup = QuerySupervisor(q)  # same loop, no controller
            dt = _drive(sup, q)
            done = q.last_committed() + 1
            q.stop()
            src.close()
            sup.close()
            rows = stream_rows if done == n_files else sum(
                p["numInputRows"] for p in q.recentProgress
            )
            return {"rows_per_s": rows / dt, "dt": dt, "rows": rows}

        def run_cold(rep):
            """Cold defaults = the serve CLI's untuned flag values
            (depth 2, prefetch 2, 4 workers, no buckets); the
            controller's learning curve runs INSIDE the timed window
            (supervisor ticks = controller ticks, windows every
            BENCH11_CTL_INTERVAL; the delivery-thread mode is
            structural, depth is the knob)."""
            src = FileStreamSource(
                in_single, prefetch_batches=2, read_workers=4,
            )
            q = StreamingQuery(
                cold_pred, src,
                CsvDirSink(os.path.join(tmp, f"out_c{rep}"),
                           durable=False),
                os.path.join(tmp, f"ckpt_c{rep}"),
                max_batch_offsets=1, wal_mode="append",
                pipeline_depth=2, overlap_sink=True,
            )
            sup = QuerySupervisor(
                q, slo=SloPolicy(slo_min_rows_per_sec=1e9),
                controller_policy=ctl_policy,
            )
            sup.controller.interval_ticks = BENCH11_CTL_INTERVAL
            dt = _drive(sup, q)
            done = q.last_committed() + 1
            ctl = sup.controller
            rec = {
                "rows_per_s": (
                    stream_rows if done == n_files else sum(
                        p["numInputRows"] for p in q.recentProgress
                    )
                ) / dt,
                "dt": dt,
                "final_knobs": ctl.knob_values(),
                "windows": ctl.guard.windows,
                "applied": len(ctl.guard.applied()),
                "delegated": ctl.delegated_total,
                "decisions": list(ctl.guard.decisions),
                "ingest": {
                    k: v for k, v in (ctl.stats().get("ingest") or
                                      {}).items()
                },
                "slo": ctl.slo_status(),
            }
            q.stop()
            src.close()
            sup.close()
            return rec

        hand_reps, cold_reps = [], []
        for rep in range(BENCH11_REPS):  # interleaved, config-5 style
            hand_reps.append(run_hand(rep))
            cold_reps.append(run_cold(rep))
        hand_med = sorted(
            hand_reps, key=lambda r: r["rows_per_s"]
        )[len(hand_reps) // 2]
        cold_med = sorted(
            cold_reps, key=lambda r: r["rows_per_s"]
        )[len(cold_reps) // 2]

        # ---- arm B: 10-tenant daemon, config-8 flag set vs cold+ctl --
        preds = {
            "hand": (
                BatchPredictor(lr_model,
                               bucket_rows=BENCH5_SHAPE_BUCKETS),
                BatchPredictor(nb_model,
                               bucket_rows=BENCH5_SHAPE_BUCKETS),
            ),
            "ctl": (
                BatchPredictor(lr_model, bucket_rows=0),
                BatchPredictor(nb_model, bucket_rows=0),
            ),
        }
        tenant_rows = {}
        daemon_chunks = set()
        for i in range(BENCH11_TENANTS):
            tid = (
                f"lr{i:02d}" if i < BENCH11_LR_TENANTS else f"nb{i:02d}"
            )
            t_sizes = _write_bench5_stream(
                os.path.join(tmp, "in", tid), test,
                chunk_cycle=BENCH11_SIZES,
            )
            tenant_rows[tid] = sum(t_sizes)
            daemon_chunks.update(t_sizes)
        def run_daemon(arm):
            lr_p, nb_p = preds[arm]
            specs = []
            for tid in tenant_rows:
                specs.append(TenantSpec(
                    tenant_id=tid,
                    model=lr_p if tid.startswith("lr") else nb_p,
                    watch=os.path.join(tmp, "in", tid),
                    sink=CsvDirSink(
                        os.path.join(tmp, "out_d", arm, tid),
                        columns=["prediction"], durable=False,
                    ),
                    max_batch_offsets=1, max_batch_failures=2,
                    # achievable setpoints (comment at the top of
                    # this config): the controller protects them
                    slo_p99_ms=(250.0 if arm == "ctl" else None),
                    slo_min_rows_per_sec=(
                        500.0 if arm == "ctl" else None
                    ),
                ))
            daemon = ServeDaemon(
                specs, os.path.join(tmp, f"root_{arm}"),
                shape_buckets=0,
                controller=(arm == "ctl"),
                controller_policy=ctl_policy,
            )
            try:
                t0 = time.perf_counter()
                daemon.process_available()
                dt = time.perf_counter() - t0
                snap = {
                    t.spec.tenant_id: t.snapshot()
                    for t in daemon.tenants
                }
                out = {
                    "dt": dt,
                    "rows": sum(s["rows_done"] for s in snap.values()),
                    "p99": {
                        tid: s["p99_ms"] for tid, s in snap.items()
                    },
                }
                if daemon.controller is not None:
                    ctl = daemon.controller
                    out["final_knobs"] = ctl.knob_values()
                    out["windows"] = ctl.guard.windows
                    out["applied"] = len(ctl.guard.applied())
                    out["delegated"] = ctl.delegated_total
                    out["decisions"] = list(ctl.guard.decisions)
                    out["slo"] = {
                        tid: {
                            "compliant": row["compliant"],
                            "axes": row["axes"],
                        }
                        for tid, row in ctl.slo_status().items()
                    }
                return out
            finally:
                daemon.close()

        # warm every arm's predictors on every distinct chunk shape
        # (incl. the ragged tail) so the measured windows are cache-hot
        for lr_p, nb_p in preds.values():
            for c in sorted(daemon_chunks):
                lr_p.predict_frame(test.slice(0, c))
                nb_p.predict_frame(test.slice(0, c))
        d_hand = run_daemon("hand")
        d_ctl = run_daemon("ctl")
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)

    hand_agg = sum(tenant_rows.values()) / d_hand["dt"]
    ctl_agg = sum(tenant_rows.values()) / d_ctl["dt"]
    ratios = [
        d_ctl["p99"][tid] / d_hand["p99"][tid]
        for tid in d_ctl["p99"]
        if d_hand["p99"].get(tid) and d_ctl["p99"][tid] is not None
    ]
    evidence = {
        "single_stream": {
            "hand_tuned_flags": {
                "shape_buckets": BENCH5_SHAPE_BUCKETS,
                "pipeline_depth": BENCH5_PIPELINE_DEPTH,
                "prefetch_batches": BENCH5_PREFETCH,
            },
            "hand_tuned_rows_per_s": round(hand_med["rows_per_s"], 1),
            "controller_rows_per_s": round(cold_med["rows_per_s"], 1),
            "controller_vs_hand_tuned": _round_ratio(
                cold_med["rows_per_s"] / hand_med["rows_per_s"]
            ),
            "final_knobs": cold_med["final_knobs"],
            "windows": cold_med["windows"],
            "applied": cold_med["applied"],
            "delegated": cold_med["delegated"],
            "decision_journal": cold_med["decisions"],
            "ingest_tuners": cold_med["ingest"],
            "slo_compliance": cold_med["slo"],
        },
        "daemon": {
            "tenants": BENCH11_TENANTS,
            "hand_tuned_flags": {
                "shape_buckets": BENCH5_SHAPE_BUCKETS,
            },
            "hand_tuned_rows_per_s": round(hand_agg, 1),
            "controller_rows_per_s": round(ctl_agg, 1),
            "controller_vs_hand_tuned": _round_ratio(
                ctl_agg / hand_agg
            ),
            "well_behaved_p99_ratio_worst": (
                _round_ratio(max(ratios)) if ratios else None
            ),
            "final_knobs": d_ctl.get("final_knobs"),
            "windows": d_ctl.get("windows"),
            "applied": d_ctl.get("applied"),
            "delegated": d_ctl.get("delegated"),
            "decision_journal": d_ctl.get("decisions"),
            "slo_compliance": d_ctl.get("slo"),
        },
    }
    return {
        "metric": "cicids2017_slo_controller_rows_per_s",
        "_datasets": (train, test),
        "value": cold_med["rows_per_s"],
        "unit": "rows/s",
        "quality": {"controller": evidence},
        "n_rows": stream_rows,
    }


# config 12: the durable-storage soak (r17).  The question: does the
# storage lifecycle actually BOUND the checkpoint-root footprint over a
# long multi-cycle run — append-WAL compaction + journal rotation +
# dead-letter retention all firing — and does the bounding cost
# throughput?  Two arms serve the SAME growing file stream through the
# same compiled predictor, cycle-interleaved on one host state: the
# "lifecycle" arm with the r17 bounds armed (compaction every
# BENCH12_COMPACT_EVERY commits, dead-letter keep-N, rotating
# journals), the "unbounded" arm with every bound disabled (the pre-r17
# grow-forever behavior).  Each cycle appends fresh CSV micro-batches
# (the first file of every cycle carries one ragged line, so the
# salvage + row-dead-letter path genuinely writes each cycle) and each
# arm drains them; after every cycle the arm's checkpoint-root bytes
# are measured.  Evidence: the lifecycle arm's footprint PLATEAUS
# (last-cycle bytes within ~1.25x of mid-run) while the unbounded
# arm's grows monotonically, and lifecycle rows/s >= 0.98x unbounded.
BENCH12_CYCLES = 12
BENCH12_CHUNK = (512, 384)
BENCH12_ROWS_PER_CYCLE = 12288
# a compaction costs ~13 ms on this host (fsync'd checkpoint publish +
# dir fsync + log reopens) REGARDLESS of interval, so the interval sets
# the amortized overhead: the production default (256) is ~0.2%, a toy
# interval of 8 would bench the fsync, not the lifecycle.  48 keeps the
# soak 5x more aggressive than the default while leaving the fixed cost
# under ~1% of serve time — and still fires every other cycle.
BENCH12_COMPACT_EVERY = 48
BENCH12_DEAD_LETTER_KEEP = 8


def bench_config12(n_rows, mesh):
    """Durable-storage soak: bounded vs unbounded artifact lifecycle
    over a multi-cycle stream (docs/RESILIENCE.md "Durable storage
    lifecycle")."""
    import shutil
    import tempfile

    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.data import CICIDS2017_CONTRACT, CICIDS2017_FEATURES
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.resilience.storage import StoragePlane
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)
    serve_model = compile_serving(PipelineModel(stages=pipe.getStages()[1:]))
    cycle_frame = test.slice(0, min(test.num_rows, BENCH12_ROWS_PER_CYCLE))
    contract = CICIDS2017_CONTRACT.with_mode("salvage")

    tmp = tempfile.mkdtemp()
    arms = {
        "lifecycle": dict(
            wal_compact_every=BENCH12_COMPACT_EVERY,
            dead_letter_keep=BENCH12_DEAD_LETTER_KEEP,
        ),
        "unbounded": dict(wal_compact_every=0, dead_letter_keep=0),
    }
    try:
        watch = os.path.join(tmp, "in")
        os.makedirs(watch)
        # ONE warmed predictor serves both arms: identical compiled
        # programs, identical warmup state, the ratio isolates the
        # storage lifecycle alone
        predictor = BatchPredictor(
            serve_model, bucket_rows=BENCH5_SHAPE_BUCKETS
        )
        for c in sorted(set(BENCH12_CHUNK)):
            predictor.predict_frame(test.slice(0, c))
        ctx = {}
        for name, kwargs in arms.items():
            src = FileStreamSource(watch, parse_salvage=True)
            q = StreamingQuery(
                predictor, src,
                CsvDirSink(os.path.join(tmp, f"out_{name}"),
                           durable=False),
                os.path.join(tmp, f"ckpt_{name}"),
                max_batch_offsets=1, wal_mode="append",
                schema_contract=contract, row_policy="salvage",
                **kwargs,
            )
            ctx[name] = {
                "q": q, "src": src, "serve_s": 0.0, "rows": 0,
                "bytes_per_cycle": [],
                "plane": StoragePlane(
                    os.path.join(tmp, f"ckpt_{name}"),
                    min_interval_s=0.0,
                ),
            }

        file_idx = 0
        total_sizes = []
        for cycle in range(BENCH12_CYCLES):
            # append this cycle's micro-batches to the shared stream
            first_of_cycle = None
            i = 0
            while i < cycle_frame.num_rows:
                size = BENCH12_CHUNK[file_idx % len(BENCH12_CHUNK)]
                chunk = cycle_frame.slice(
                    i, min(i + size, cycle_frame.num_rows)
                )
                path = os.path.join(watch, f"part_{file_idx:06d}.csv")
                pacsv.write_csv(
                    chunk.select(CICIDS2017_FEATURES).to_arrow(), path
                )
                if first_of_cycle is None:
                    first_of_cycle = path
                i += chunk.num_rows
                file_idx += 1
                total_sizes.append(chunk.num_rows)
            # one ragged line per cycle: the salvage + row-dead-letter
            # paths write every cycle, so retention has real work
            with open(first_of_cycle, "a") as f:
                f.write("1,2,3\n")
            # settle the kernel's writeback of the ~megabytes just
            # written OUTSIDE the timed windows — otherwise the first
            # arm to serve each cycle races the flush and the ratio
            # measures dirty-page pressure, not the storage lifecycle
            os.sync()
            # alternate which arm serves the fresh files first: the
            # first reader pays the cold page-cache parse, and 12
            # cycles of always-first would bias the ratio against it
            order = list(ctx.items())
            if cycle % 2:
                order.reverse()
            for name, c in order:
                t0 = time.perf_counter()
                n_done = c["q"].process_available()
                dt = time.perf_counter() - t0
                c["serve_s"] += dt
                c.setdefault("cycle_s", []).append(dt)
                if n_done:  # [-0:] would re-count the whole ring
                    c["rows"] += sum(
                        p["numInputRows"]
                        for p in c["q"].recentProgress[-n_done:]
                    )
                c["bytes_per_cycle"].append(
                    c["plane"].usage()["total_bytes"]
                )
                # the sink output is the PRODUCT, not a lifecycle
                # artifact: clear it between cycles so the soak's disk
                # use is the checkpoint trees under test
                for p in glob.glob(
                    os.path.join(tmp, f"out_{name}", "batch_*.csv")
                ):
                    os.unlink(p)
        evidence = {}
        for name, c in ctx.items():
            series = c["bytes_per_cycle"]
            mid = series[len(series) // 2]
            evidence[name] = {
                "rows_per_s": round(c["rows"] / c["serve_s"], 1),
                "rows": c["rows"],
                "serve_s": round(c["serve_s"], 3),
                "ckpt_bytes_per_cycle": series,
                "ckpt_bytes_final": series[-1],
                "final_over_mid": _round_ratio(series[-1] / mid),
                "storage": c["q"].storage_stats(),
            }
            c["q"].stop()
            c["src"].close()
        life, unb = evidence["lifecycle"], evidence["unbounded"]
        # per-cycle throughput ratio, MEDIAN-reported: both arms serve
        # identical rows each cycle, so the ratio per cycle is just
        # dt_unbounded/dt_lifecycle — and the median is robust to one
        # host-throttling burst landing inside a single arm's window
        # (the config-5 median-rep discipline applied per cycle)
        cycle_ratios = [
            u / l for l, u in zip(
                ctx["lifecycle"]["cycle_s"], ctx["unbounded"]["cycle_s"]
            )
        ]
        median_ratio = sorted(cycle_ratios)[len(cycle_ratios) // 2]
        storage_evidence = {
            "cycles": BENCH12_CYCLES,
            "stream_files": file_idx,
            "stream_rows": sum(total_sizes),
            "lifecycle": life,
            "unbounded": unb,
            # the two acceptance verdicts, precomputed for the journal
            "footprint_plateaued": life["final_over_mid"] <= 1.25,
            "unbounded_growth_ratio": _round_ratio(
                unb["ckpt_bytes_final"] / life["ckpt_bytes_final"]
            ),
            "rows_per_s_ratio_vs_unbounded": _round_ratio(median_ratio),
            "cycle_ratios": [_round_ratio(r) for r in cycle_ratios],
            "aggregate_ratio": _round_ratio(
                life["rows_per_s"] / unb["rows_per_s"]
            ),
            "wal_compactions": life["storage"]["wal_compactions"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "cicids2017_storage_soak_rows_per_s",
        "_datasets": (train, test),
        "value": life["rows_per_s"], "unit": "rows/s",
        "quality": {"storage_soak": storage_evidence},
        "n_rows": life["rows"],
    }


# config 13: the mid-stream device-fault storm (r18).  The question:
# does the compute-plane fault domain actually SURVIVE realistic device
# failure — seeded OOM bursts, one poisoned compile signature, and a
# device-lost/recover arc, all landing mid-stream — without losing or
# duplicating a single batch, and what does degraded-mode serving cost?
# Two arms serve the SAME file stream through identical fused+bucketed
# predictors (domains armed on both; faults injected only in the storm
# arm), phase by phase:
#   A  OOM burst    — device.dispatch:device_oom seeded-probabilistic:
#                     the splitter halves batches and retries on device
#   B  poison       — fuse.compile:compile_error on a FRESH signature
#                     (a new batch size): exactly one (segment,
#                     signature) leaves the device plan cache; its
#                     batches serve the eager host fallback
#   C  lost/recover — device.dispatch:device_lost once: HOST_DEGRADED
#                     serving (the degraded rows/s floor) until the
#                     probe-gated recovery tick restores the device
# Evidence: commits identical (zero lost/duplicated batches), sink
# files byte-identical (the tolerance contract's bitwise half: the
# sink carries the f64 prediction column), per-phase rows/s, the
# degraded-mode floor, and the recovery latency — all journaled.
BENCH13_PHASE_FILES = (6, 4, 6)
BENCH13_CHUNK = (384, 700, 384)  # phase B's 700 is a FRESH bucket
BENCH13_SHAPE_BUCKETS = 256


def bench_config13(n_rows, mesh):
    """Mid-stream device-fault storm vs an unfaulted reference
    (docs/RESILIENCE.md "Compute-plane fault domain")."""
    import shutil
    import tempfile

    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.resilience import (
        DeviceFaultDomain,
        DevicePolicy,
        arm,
        clear,
    )
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
        compile_serving,
    )

    train, test = _dataset(n_rows, binary=True)
    # the config-6 fused pipeline (the scaler fold can't absorb the
    # DCT/PCA run, so compile_serving yields a REAL fused segment —
    # the fuse.compile boundary phase B poisons genuinely exists)
    from sntc_tpu.feature import DCT, MinMaxScaler, PCA

    pipe = Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
        MinMaxScaler(inputCol="rawFeatures", outputCol="mm"),
        DCT(inputCol="mm", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="features",
            k=BENCH6_PCA_K),
        LogisticRegression(mesh=mesh, maxIter=20),
    ]).fit(train)
    serve_model = PipelineModel(stages=pipe.getStages()[1:])

    tmp = tempfile.mkdtemp()
    try:
        watch = os.path.join(tmp, "in")
        os.makedirs(watch)
        arms = {}
        for name in ("reference", "storm"):
            # degrade_after=2: one isolated poisoned compile must NOT
            # flip HOST_DEGRADED (the poison response absorbs it);
            # device_lost degrades unconditionally
            dom = DeviceFaultDomain(
                DevicePolicy(probe_interval_s=0.0, degrade_after=2),
                probe_fn=lambda: True, probe_async=False,
            )
            pred = BatchPredictor(
                compile_serving(serve_model),
                bucket_rows=BENCH13_SHAPE_BUCKETS, device_domain=dom,
            )
            q = StreamingQuery(
                pred, FileStreamSource(watch),
                CsvDirSink(os.path.join(tmp, f"out_{name}"),
                           durable=False),
                os.path.join(tmp, f"ckpt_{name}"),
                max_batch_offsets=1, max_batch_failures=3,
            )
            arms[name] = {"q": q, "dom": dom, "pred": pred,
                          "phase_s": [], "phase_rows": []}

        # the storm arm's per-phase injections (programmatic arming:
        # deterministic seeded schedules, exactly like the chaos tests)
        storm_faults = (
            lambda: arm("device.dispatch", "device_oom", prob=0.35,
                        seed=7, times=None),
            lambda: arm("fuse.compile", "compile_error", times=1),
            lambda: arm("device.dispatch", "device_lost", times=1),
        )
        # one phase at a time: write the phase's files, arm the storm
        # arm's fault, serve both arms to the new high-water mark —
        # the faults land genuinely MID-STREAM, with committed batches
        # already behind them
        file_idx = 0
        src_rows = 0
        for phase, n_files in enumerate(BENCH13_PHASE_FILES):
            size = BENCH13_CHUNK[phase]
            lo = file_idx
            for _ in range(n_files):
                at = (file_idx * 131) % max(1, test.num_rows - size)
                chunk = test.slice(at, at + size)
                pacsv.write_csv(
                    chunk.select(CICIDS2017_FEATURES).to_arrow(),
                    os.path.join(watch, f"part_{file_idx:06d}.csv"),
                )
                src_rows += chunk.num_rows
                file_idx += 1
            hi = file_idx
            for name, c in arms.items():
                clear()
                if name == "storm":
                    storm_faults[phase]()
                t0 = time.perf_counter()
                # a deferred device-classified batch replays next round
                for _ in range(12):
                    c["q"].process_available()
                    if c["q"].last_committed() + 1 >= hi:
                        break
                dt = time.perf_counter() - t0
                clear()
                rows = sum(
                    p["numInputRows"]
                    for p in c["q"].recentProgress[-(hi - lo):]
                )
                c["phase_s"].append(dt)
                c["phase_rows"].append(rows)
        # drive the recovery tick to completion on the storm arm (the
        # sync probe recovers on the first post-fault round; phase C
        # already served through it, so this is only a guard)
        storm = arms["storm"]
        for _ in range(3):
            if not storm["dom"].host_degraded:
                break
            storm["dom"].tick()

        def _commits(name):
            d = os.path.join(tmp, f"ckpt_{name}", "commits")
            return sorted(
                os.path.basename(p) for p in glob.glob(
                    os.path.join(d, "*.json"))
            )

        def _sink_bytes(name):
            out = {}
            for p in sorted(glob.glob(
                os.path.join(tmp, f"out_{name}", "batch_*.csv")
            )):
                with open(p, "rb") as f:
                    out[os.path.basename(p)] = f.read()
            return out

        commits_match = _commits("reference") == _commits("storm")
        ref_sink, storm_sink = _sink_bytes("reference"), _sink_bytes(
            "storm")
        sink_match = ref_sink == storm_sink
        dev = storm["dom"].stats()
        ref = arms["reference"]
        phases = []
        for i, label in enumerate(("oom_burst", "poisoned_signature",
                                   "device_lost_recover")):
            phases.append({
                "phase": label,
                "files": BENCH13_PHASE_FILES[i],
                "rows_per_s": round(
                    storm["phase_rows"][i] / storm["phase_s"][i], 1
                ),
                "reference_rows_per_s": round(
                    ref["phase_rows"][i] / ref["phase_s"][i], 1
                ),
            })
        for name, c in arms.items():
            c["q"].stop()
        storm_evidence = {
            "stream_files": file_idx,
            "stream_rows": src_rows,
            "zero_lost_or_duplicated": commits_match,
            "sink_bitwise_match": sink_match,
            "sink_files": len(storm_sink),
            "phases": phases,
            # the degraded-mode floor: phase C served HOST_DEGRADED
            # until the probe-gated tick recovered the device
            "degraded_rows_per_s_floor": phases[2]["rows_per_s"],
            "degraded_over_reference": _round_ratio(
                phases[2]["rows_per_s"]
                / phases[2]["reference_rows_per_s"]
            ),
            "recovery_latency_s": dev["recovery_latency_s"],
            "device": {
                k: dev[k] for k in (
                    "state", "faults", "oom_splits",
                    "bucket_floor_steps", "poisoned_signatures",
                    "fallback_batches", "degradations", "recoveries",
                )
            },
        }
        total_rows = sum(storm["phase_rows"])
        total_s = sum(storm["phase_s"])
    finally:
        clear()
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "cicids2017_device_storm_rows_per_s",
        "_datasets": (train, test),
        "value": round(total_rows / total_s, 1), "unit": "rows/s",
        "quality": {"device_storm": storm_evidence},
        "n_rows": total_rows,
    }


# config 14: elastic-fleet worker-death recovery (r19).  The question:
# when one of three REAL worker processes is SIGKILLed mid-stream, does
# the coordinator's lease-expiry → dead-source migration path actually
# deliver zero committed-row loss AND recovered throughput?  Two
# passes serve the SAME 10-tenant file stream through a 3-worker fleet
# (in-process coordinator — its sntc_fleet_* series land in this
# process's obs delta — real `fleet-serve --fleet-worker-id` worker
# children): a reference pass runs unkilled; the kill pass SIGKILLs
# the most-loaded worker once every tenant has committed batches, then
# scales out a replacement (the elastic half: a fresh worker earns its
# consistent-hash share through the same migration path) and phase-2
# files land only after the fleet has re-converged.  Evidence:
# per-tenant sink unions byte-identical across the passes (zero rows
# lost or duplicated through the kill + migrations), the recovery
# latency, and post-recovery rows/s against the reference's.
BENCH14_WORKERS = 3
BENCH14_TENANTS = 10
BENCH14_PHASE_FILES = (3, 3)  # per tenant: pre-kill, post-recovery


def bench_config14(n_rows, mesh):
    """Fleet worker-death recovery vs an unkilled reference
    (docs/RESILIENCE.md "Elastic serve fleet")."""
    import shutil
    import subprocess
    import tempfile
    from types import SimpleNamespace

    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.mlio import save_model
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve.fleet import FleetCoordinator

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)

    n_files = sum(BENCH14_PHASE_FILES)
    chunk = max(96, min(512, n_rows // 120))
    tids = [f"t{i}" for i in range(BENCH14_TENANTS)]
    worker_ids = [f"w{i}" for i in range(BENCH14_WORKERS)]
    tmp = tempfile.mkdtemp()
    try:
        model_dir = os.path.join(tmp, "model")
        save_model(pipe, model_dir)
        # stage every input file ONCE: both passes serve identical bytes
        staging = os.path.join(tmp, "staging")
        os.makedirs(staging)
        rows_per_file = {}
        for ti, tid in enumerate(tids):
            for fi in range(n_files):
                at = ((ti * n_files + fi) * 131) % max(
                    1, test.num_rows - chunk
                )
                part = test.slice(at, at + chunk)
                pacsv.write_csv(
                    part.select(CICIDS2017_FEATURES).to_arrow(),
                    os.path.join(staging, f"{tid}_part_{fi:03d}.csv"),
                )
                rows_per_file[tid, fi] = part.num_rows

        def _feed(pass_dir, tid, lo, hi):
            for fi in range(lo, hi):
                src = os.path.join(staging, f"{tid}_part_{fi:03d}.csv")
                dst = os.path.join(
                    pass_dir, "in", tid, f"part_{fi:03d}.csv"
                )
                shutil.copy(src, dst + ".tmp")
                os.rename(dst + ".tmp", dst)

        def _batches(pass_dir, tid):
            return sorted(glob.glob(os.path.join(
                pass_dir, "out", tid, "batch_*.csv"
            )))

        def _rows_done(pass_dir):
            done = 0
            for tid in tids:
                for p in _batches(pass_dir, tid):
                    with open(p, "rb") as f:
                        done += max(0, f.read().count(b"\n") - 1)
            return done

        def _run_pass(name, kill):
            pass_dir = os.path.join(tmp, name)
            root = os.path.join(pass_dir, "root")
            entries = []
            for tid in tids:
                os.makedirs(os.path.join(pass_dir, "in", tid))
                entries.append({
                    "id": tid, "model": model_dir,
                    "watch": os.path.join(pass_dir, "in", tid),
                    "out": os.path.join(pass_dir, "out", tid),
                })
                _feed(pass_dir, tid, 0, BENCH14_PHASE_FILES[0])
            tenants_json = os.path.join(pass_dir, "tenants.json")
            with open(tenants_json, "w") as f:
                json.dump({"tenants": entries}, f)
            coord = FleetCoordinator(
                root, worker_ids,
                {tid: SimpleNamespace(placement_cost=None, weight=1.0,
                                      pinned_worker=None)
                 for tid in tids},
                lease_ttl_s=1.0, boot_grace_s=600.0,
            )
            argv = [
                sys.executable, "-m", "sntc_tpu", "fleet-serve",
                "--tenants", tenants_json, "--root", root,
                "--poll-interval", "0.05", "--no-device-faults",
            ]
            procs = {
                wid: subprocess.Popen(
                    argv + ["--fleet-worker-id", wid],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
                for wid in worker_ids
            }

            def _wait(pred, what, timeout=600.0):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    coord.tick()
                    if pred():
                        return
                    time.sleep(0.05)
                raise RuntimeError(
                    f"config 14 {name}: timed out waiting for {what}"
                )

            out = {}
            try:
                # mid-stream milestone: every tenant has committed
                # batches, every worker is carrying real load
                _wait(
                    lambda: all(_batches(pass_dir, t) for t in tids),
                    "first committed batch per tenant",
                )
                t_mid = time.perf_counter()
                rows_mid = _rows_done(pass_dir)
                if kill:
                    victim = max(
                        worker_ids,
                        key=lambda w: sum(
                            1 for e in coord.assignments.values()
                            if e["worker"] == w
                        ),
                    )
                    out["killed_worker"] = victim
                    out["dead_tenants"] = sorted(
                        t for t, e in coord.assignments.items()
                        if e["worker"] == victim
                    )
                    procs[victim].kill()
                    procs[victim].wait()
                    _wait(
                        lambda: (
                            coord.status()["workers"][victim]["state"]
                            == "dead"
                            and all(
                                e["phase"] == "serving"
                                and e["worker"] != victim
                                for e in coord.assignments.values()
                            )
                        ),
                        "dead-worker recovery",
                    )
                    out["recovery_s"] = round(
                        time.perf_counter() - t_mid, 2
                    )
                    # the elastic half: a replacement worker joins and
                    # earns its consistent-hash share back through the
                    # same migration path, restoring fleet capacity
                    newid = f"w{BENCH14_WORKERS}"
                    procs[newid] = subprocess.Popen(
                        argv + ["--fleet-worker-id", newid],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                    coord.add_worker(newid)
                    out["scaled_out_worker"] = newid
                    _wait(
                        lambda: (
                            coord.status()["workers"][newid]["state"]
                            == "live"
                            and all(
                                e["phase"] == "serving"
                                for e in coord.assignments.values()
                            )
                        ),
                        "scale-out worker joining",
                    )
                # phase 2: the post-recovery (or reference) window
                t2 = time.perf_counter()
                for tid in tids:
                    _feed(pass_dir, tid, BENCH14_PHASE_FILES[0],
                          n_files)
                _wait(
                    lambda: all(
                        len(_batches(pass_dir, t)) == n_files
                        for t in tids
                    ),
                    "every tenant fully served",
                )
                t_end = time.perf_counter()
                rows_end = _rows_done(pass_dir)
                out["rows"] = rows_end
                out["rows_per_s"] = round(
                    (rows_end - rows_mid) / (t_end - t_mid), 1
                )
                phase2_rows = sum(
                    rows_per_file[t, fi] for t in tids
                    for fi in range(BENCH14_PHASE_FILES[0], n_files)
                )
                out["recovered_rows_per_s"] = round(
                    phase2_rows / (t_end - t2), 1
                )
                out["migrations"] = dict(coord.migrations)
                out["sinks"] = {
                    tid: {
                        os.path.basename(p): open(p, "rb").read()
                        for p in _batches(pass_dir, tid)
                    }
                    for tid in tids
                }
            finally:
                coord.drain_fleet("bench_complete")
                deadline = time.time() + 60
                for p in procs.values():
                    if p.poll() is None:
                        try:
                            p.wait(timeout=max(
                                0.1, deadline - time.time()
                            ))
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait()
                coord.tick()
                coord.close()
            return out

        ref = _run_pass("reference", kill=False)
        killed = _run_pass("killed", kill=True)
        sink_match = all(
            killed["sinks"][t] == ref["sinks"][t] for t in tids
        )
        fleet_evidence = {
            "workers": BENCH14_WORKERS,
            "tenants": BENCH14_TENANTS,
            "stream_files": BENCH14_TENANTS * n_files,
            "killed_worker": killed["killed_worker"],
            "scaled_out_worker": killed["scaled_out_worker"],
            "dead_tenants_migrated": len(killed["dead_tenants"]),
            "migrations": killed["migrations"],
            "recovery_s": killed["recovery_s"],
            # the headline invariants: nothing lost through the kill,
            # throughput back after the survivors absorb the load
            "zero_committed_rows_lost": sink_match,
            "recovered_rows_per_s": killed["recovered_rows_per_s"],
            "reference_rows_per_s": ref["recovered_rows_per_s"],
            "recovered_over_reference": _round_ratio(
                killed["recovered_rows_per_s"]
                / ref["recovered_rows_per_s"]
            ),
        }
        total_rows = killed["rows"]
        value = killed["rows_per_s"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "cicids2017_fleet_recovery_rows_per_s",
        "_datasets": (train, test),
        "value": value, "unit": "rows/s",
        "quality": {"fleet_recovery": fleet_evidence},
        "n_rows": total_rows,
    }


# config 15: the live network front door (r20).  The config-9 question
# asked of the socket path: does WAL-at-ingress (recv → bounded ring →
# fsynced atomic seal → spool replay) cost meaningfully more than
# serving the SAME capture files dropped straight into a watched
# directory?  Both passes serve identical payload bytes through ONE
# shared predictor; the socket pass is timed from the first datagram
# sent to the last batch committed, with a windowed sender (at most a
# few datagrams outstanding past the spool's received count) and
# seal_every=BENCH15_SEAL_EVERY, so the measured cost includes every
# fsynced atomic seal the durability contract demands at the spool's
# real batching cadence.  A kill leg rides along via the chaos harness:
# SIGKILL inside the seal mid-traffic, restart, resend — committed
# state and sink bytes must converge bitwise with an unkilled
# reference, with sent == committed + journaled_drops exact.
BENCH15_REPS = 3
BENCH15_FLOWS_PER_FILE = 192
BENCH15_PACKETS_PER_FLOW = 4
BENCH15_SEAL_EVERY = 4


def bench_config15(n_rows, mesh):
    """Socket-fed ingress vs the directory path on identical payloads
    (docs/RESILIENCE.md "Network ingress")."""
    import importlib.util
    import shutil
    import tempfile

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.data.synth import write_capture_stream
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        StreamingQuery,
        build_ingress,
        compile_serving,
        wire_committed_offset,
    )
    from sntc_tpu.serve.netflow_source import NetFlowDirSource

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)
    predictor = BatchPredictor(
        compile_serving(PipelineModel(stages=pipe.getStages()[1:])),
        bucket_rows=BENCH9_SHAPE_BUCKETS,
    )
    # a multiple of the socket pass's seal factor: every sealed spool
    # file is exactly BENCH15_SEAL_EVERY payloads, no idle tail seal
    # inside the timed window
    n_files = max(4, min(64, n_rows // 1024))
    n_files -= n_files % BENCH15_SEAL_EVERY

    def timed_pass(tmp, name, rep, source):
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            predictor, source,
            CsvDirSink(out_dir, columns=["prediction"], durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=1, wal_mode="append",
        )
        t0 = time.perf_counter()
        q.process_available()
        dt = time.perf_counter() - t0
        q.stop()
        source.close()
        return dt, out_dir

    def socket_pass(tmp, rep, payloads):
        import socket as socketlib

        spool_dir = os.path.join(tmp, f"spool_{rep}")
        out_dir = os.path.join(tmp, f"out_sock_{rep}")
        # seal_every=4: the spool batches datagrams per capture file
        # (its design default); the sink comparison below is row-for-
        # row over concatenated output, so file-boundary differences
        # vs the directory pass don't matter — row ORDER does, and it
        # is identical
        source, listeners = build_ingress(
            spool_dir, listen_udp=0, seal_every=BENCH15_SEAL_EVERY,
            seal_idle_s=0.05, ring=max(64, 2 * len(payloads)),
            keep_files=10**6,
        )
        q = StreamingQuery(
            predictor, source,
            CsvDirSink(out_dir, columns=["prediction"], durable=False),
            os.path.join(tmp, f"ckpt_sock_{rep}"),
            max_batch_offsets=1, wal_mode="append",
        )
        wire_committed_offset(source, q.committed_end)
        lst = listeners[0].start()
        spool = lst.spool
        tx = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_DGRAM)
        t0 = time.perf_counter()
        try:
            # windowed send (the ring holds 2x the whole set, so OUR
            # side never overflows; the window keeps at most 4 full
            # datagrams in the KERNEL receive buffer, which is the
            # only uncounted drop point on loopback) and serve WHILE
            # the spooler seals: the timed window covers first
            # datagram to last commit, fsync chain and engine compute
            # overlapped — the live shape.  Any loss still fails the
            # run below.
            for i, payload in enumerate(payloads):
                tx.sendto(payload, ("127.0.0.1", lst.port))
                send_deadline = time.time() + 60.0
                while spool.stats.received < i - 3:
                    if time.time() > send_deadline:
                        raise RuntimeError(
                            f"config 15: receiver stalled at payload "
                            f"{i}: {spool.stats.snapshot()}"
                        )
                    time.sleep(0.0002)
            n_sealed = len(payloads) // BENCH15_SEAL_EVERY
            deadline = time.time() + 300.0
            while q.committed_end() < n_sealed:
                if q.process_available() == 0:
                    time.sleep(0.0005)
                if time.time() > deadline:
                    raise RuntimeError(
                        "config 15: socket pass never fully committed: "
                        f"{spool.stats.snapshot()}"
                    )
            dt = time.perf_counter() - t0
        finally:
            tx.close()
            lst.drain(timeout_s=10.0)
            q.stop()
            source.close()
        snap = spool.stats.snapshot()
        if snap["received"] != len(payloads) or snap["dropped"]:
            raise RuntimeError(
                f"config 15: ingress loss on loopback: {snap}"
            )
        return dt, out_dir, snap

    tmp = tempfile.mkdtemp()
    try:
        cap_dir = os.path.join(tmp, "in_cap")
        cap_info = write_capture_stream(
            cap_dir, n_files=n_files,
            flows_per_file=BENCH15_FLOWS_PER_FILE,
            packets_per_flow=BENCH15_PACKETS_PER_FLOW,
            seed=SEED, format="netflow", flush=False,
        )
        files = sorted(glob.glob(os.path.join(cap_dir, "*.nf5")))
        payloads = []
        for p in files:
            with open(p, "rb") as f:
                payloads.append(f.read())
        if any(len(p) > 60_000 for p in payloads):
            raise RuntimeError(
                "config 15: a capture file exceeds one UDP datagram"
            )
        # untimed reference decode: row count + predictor shape warmup
        ref_src = NetFlowDirSource(cap_dir)
        feature_rows = 0
        for i in range(ref_src.latest_offset()):
            f = ref_src.get_batch(i, i + 1)
            feature_rows += f.num_rows
            if f.num_rows:
                predictor.predict_frame(f)
        ref_src.close()
        # one untimed warmup pass through the engine paths
        timed_pass(tmp, "dirwarm", 0, NetFlowDirSource(cap_dir))
        reps = {"dir": [], "sock": []}
        sock_stats = None
        for rep in range(BENCH15_REPS):
            dt, out_sock, sock_stats = socket_pass(tmp, rep, payloads)
            reps["sock"].append((dt, out_sock))
            dt, out_dir = timed_pass(
                tmp, "dir", rep, NetFlowDirSource(cap_dir)
            )
            reps["dir"].append((dt, out_dir))
        med = {k: sorted(v)[len(v) // 2] for k, v in reps.items()}
        # identical payloads in identical offset order: the two paths'
        # sink output must match row for row
        sink_match = _sinks_match(
            _read_sink_dir(med["sock"][1]),
            _read_sink_dir(med["dir"][1]),
        )
        # the kill leg: SIGKILL at ingress.spool mid-traffic in a real
        # child engine, restart, resend-until-sealed — bitwise
        # convergence with an unkilled reference (the chaos harness is
        # the single source of truth for the protocol)
        spec = importlib.util.spec_from_file_location(
            "chaos_crash_matrix",
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "scripts", "chaos_crash_matrix.py",
            ),
        )
        chaos = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(chaos)
        kill_dir = os.path.join(tmp, "kill_leg")
        reference = chaos.run_ingress_reference(kill_dir)
        verdict = chaos.run_ingress_kill_scenario(
            kill_dir, "ingress.spool", reference
        )
        if not verdict["ok"]:
            raise RuntimeError(f"config 15 kill leg failed: {verdict}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    sock_rows_per_s = feature_rows / med["sock"][0]
    dir_rows_per_s = feature_rows / med["dir"][0]
    evidence = {
        "capture_files": len(payloads),
        "records": int(cap_info["records"].shape[0]),
        "feature_rows": feature_rows,
        "dir_rows_per_s": round(dir_rows_per_s, 1),
        "socket_vs_dir": _round_ratio(sock_rows_per_s / dir_rows_per_s),
        "sink_match": sink_match,
        "reps": BENCH15_REPS,
        "ingress_received": sock_stats["received"],
        "ingress_spooled": sock_stats["spooled"],
        "ingress_dropped": sock_stats["dropped"],
        "kill_leg": {
            "site": "ingress.spool",
            "kills": verdict["kills"],
            "sent": verdict["sent"],
            "committed": verdict["committed"],
            "journaled_drops": verdict["journaled_drops"],
            "law_exact": verdict["law_exact"],
            "sink_bitwise": verdict["sink_bitwise"],
        },
    }
    return {
        "metric": "cicids2017_live_ingress_rows_per_s",
        "_datasets": (train, test),
        "value": sock_rows_per_s,
        "unit": "rows/s",
        "quality": {"ingress": evidence},
        "n_rows": feature_rows,
    }


# config 16: the serving-kernel forge (r21).  Same harness discipline as
# config 6 (one synthetic CSV stream, both engines warmed, reps
# interleaved, MEDIAN reported, sink bitwise-compared) but with a FOREST
# head so the kernel tier's ensemble-traversal kernel carries the hot
# path, and the two engines differ ONLY in SNTC_SERVE_KERNELS: the
# fused-XLA twin (off) vs the kernel tier (pallas on TPU, interpret
# elsewhere — on CPU the interpret emulator is expected to LOSE; the
# journaled ratio is honest either way).  SNTC_OBS_COST_ANALYSIS is on
# for both compiles, so each engine's fusion_stats carries the
# per-segment roofline (FLOPs, bytes, achieved-vs-peak MFU).  A third
# leg arms a kernel.compile fault and proves the poison ladder: the
# batch serves bitwise on the XLA twin, the kernel signature is
# poisoned, the SEGMENT is not, and zero faults reach the device domain.
BENCH16_REPS = 5


def bench_config16(n_rows, mesh):
    """Fused-XLA vs kernel-tier serving throughput (rows/s) plus the
    per-segment MFU/roofline evidence — the r21 kernel forge measured,
    not asserted."""
    import shutil
    import tempfile

    import pyarrow as pa

    import jax

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.feature import DCT, MinMaxScaler, PCA
    from sntc_tpu.fuse import compile_pipeline, fused_segments, fusion_stats
    from sntc_tpu.kernels.registry import clear_poisons, kernel_stats
    from sntc_tpu.models import RandomForestClassifier
    from sntc_tpu.resilience import faults as _faults
    from sntc_tpu.resilience.device import DeviceFaultDomain
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    kernel_mode = (
        "pallas" if jax.default_backend() == "tpu" else "interpret"
    )
    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
        MinMaxScaler(inputCol="rawFeatures", outputCol="mm"),
        DCT(inputCol="mm", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="features",
            k=BENCH6_PCA_K),
        RandomForestClassifier(mesh=mesh, numTrees=RF_TREES,
                               maxDepth=RF_DEPTH, seed=0),
    ]).fit(train)
    staged_model = PipelineModel(stages=pipe.getStages()[1:])

    def make_engine(tmp, name, in_dir, chunk_sizes, mode):
        """Compile the serving pipeline UNDER the engine's kernel mode
        (the registry decides per traced signature at compile time),
        then warm every bucketed shape through the predictor."""
        os.environ["SNTC_SERVE_KERNELS"] = mode
        model = compile_pipeline(staged_model)
        predictor = BatchPredictor(model, bucket_rows=BENCH5_SHAPE_BUCKETS)
        warm = StreamingQuery(
            predictor, FileStreamSource(in_dir),
            CsvDirSink(os.path.join(tmp, f"warm_{name}"), durable=False),
            os.path.join(tmp, f"warmckpt_{name}"),
            max_batch_offsets=1, wal_mode="append",
        )
        warm._run_one_batch()
        warm.stop()
        for c in sorted(set(chunk_sizes)):
            predictor.predict_frame(test.slice(0, c))
        return {"name": name, "mode": mode, "model": model,
                "predictor": predictor, "reps": []}

    def run_once(tmp, eng, in_dir, rep, stream_rows, n_files):
        os.environ["SNTC_SERVE_KERNELS"] = eng["mode"]
        name = eng["name"]
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            eng["predictor"], FileStreamSource(in_dir),
            CsvDirSink(out_dir, durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=1, wal_mode="append",
            pipeline_depth=1,  # serial engines: the ratio is pure tier
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        rows = (
            stream_rows
            if n_done == n_files
            else sum(p["numInputRows"] for p in q.recentProgress)
        )
        q.stop()
        eng["reps"].append({
            "out_dir": out_dir, "batches": n_done, "rows": rows,
            "dt": dt, "rows_per_s": rows / dt,
        })

    def median_rep(eng):
        reps = sorted(eng["reps"], key=lambda r: r["rows_per_s"])
        rec = dict(reps[len(reps) // 2])
        rec["best_rows_per_s"] = round(reps[-1]["rows_per_s"], 1)
        return rec

    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # same intra-op pinning discipline as config 5
    saved_env = {
        k: os.environ.get(k)
        for k in ("SNTC_SERVE_HOST_ROWS", "SNTC_SERVE_KERNELS",
                  "SNTC_OBS_COST_ANALYSIS")
    }
    os.environ["SNTC_SERVE_HOST_ROWS"] = "0"  # device path both sides
    os.environ["SNTC_OBS_COST_ANALYSIS"] = "1"  # roofline per segment
    clear_poisons()
    try:
        in_dir = os.path.join(tmp, "in")
        chunk_sizes = _write_bench5_stream(
            in_dir, test, passes=BENCH5_STREAM_PASSES
        )
        stream_rows, n_files = sum(chunk_sizes), len(chunk_sizes)
        engines = [
            make_engine(tmp, "xla", in_dir, chunk_sizes, "off"),
            make_engine(tmp, "kernel", in_dir, chunk_sizes, kernel_mode),
        ]
        kern_segments = fused_segments(engines[1]["model"])
        compiles_before = sum(s.compile_events for s in kern_segments)
        for rep in range(BENCH16_REPS):
            for eng in engines:
                run_once(tmp, eng, in_dir, rep, stream_rows, n_files)
        xla_r, kern_r = (median_rep(e) for e in engines)
        sink_match = _sinks_match(
            _read_sink_dir(xla_r["out_dir"]),
            _read_sink_dir(kern_r["out_dir"]),
        )
        kern_stats = fusion_stats(engines[1]["model"])
        recompiles = sum(
            s.compile_events for s in kern_segments
        ) - compiles_before

        # ---- poison leg: a kernel.compile fault must stay a KERNEL
        # fallback — batch bitwise on the XLA twin, segment alive,
        # domain clean.  Cost analysis goes OFF here: obs_cost.extract
        # lowers the fused program once outside the dispatch try and
        # (by contract) swallows failures there, which would absorb the
        # one-shot injected fault before the serving ladder ever saw
        # it — the leg is about the ladder, not the cost plane ----
        clear_poisons()
        os.environ.pop("SNTC_OBS_COST_ANALYSIS", None)
        os.environ["SNTC_SERVE_KERNELS"] = kernel_mode
        poison_model = compile_pipeline(staged_model)
        dom = DeviceFaultDomain()
        bp = BatchPredictor(
            poison_model, bucket_rows=BENCH5_SHAPE_BUCKETS,
            device_domain=dom,
        )
        probe = test.slice(0, BENCH5_SIZES[0])
        _faults.arm("kernel.compile", kind="compile_error", times=1)
        try:
            poisoned_out = bp.predict_frame(probe)
        finally:
            _faults.clear()
        os.environ["SNTC_SERVE_KERNELS"] = "off"
        ref_out = engines[0]["predictor"].predict_frame(probe)
        poison_bitwise = all(
            np.array_equal(
                np.asarray(poisoned_out[c]), np.asarray(ref_out[c])
            )
            for c in ("rawPrediction", "probability", "prediction")
        )
        poison_fs = fusion_stats(poison_model)
        kstats = kernel_stats()
    finally:
        pa.set_cpu_count(arrow_cpus)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        clear_poisons()
        shutil.rmtree(tmp, ignore_errors=True)
    kernel_evidence = {
        "kernel_mode": kernel_mode,
        "speedup_vs_fused_xla": _round_ratio(
            kern_r["rows_per_s"] / xla_r["rows_per_s"]
        ),
        "fused_xla_rows_per_s": round(xla_r["rows_per_s"], 1),
        "best_rows_per_s": kern_r["best_rows_per_s"],
        "fused_xla_best_rows_per_s": xla_r["best_rows_per_s"],
        "sink_match": sink_match,  # the twin pin, end to end
        "recompiles_after_warmup": recompiles,
        "fallbacks": kern_stats["fallbacks"],
        "kernels": kern_stats["kernels"],
        "roofline": kern_stats.get("roofline"),
        "reps": BENCH16_REPS,
        "batch_sizes": list(BENCH5_SIZES),
        "arrow_intra_op_threads": 1,
        "poison_leg": {
            "site": "kernel.compile",
            "sink_bitwise": poison_bitwise,
            "kernel_poisoned_signatures": (
                kstats["poisoned_signatures"]
            ),
            "segment_fallbacks": poison_fs["fallbacks"],
            "segment_poisoned_signatures": (
                poison_fs["poisoned_signatures"]
            ),
            "domain_faults": dom.fault_count(),
            "domain_state": dom.stats()["state"],
        },
    }
    ok = (
        sink_match
        and poison_bitwise
        and recompiles == 0
        and kernel_evidence["poison_leg"]["kernel_poisoned_signatures"] >= 1
        and kernel_evidence["poison_leg"]["segment_fallbacks"] == 0
        and kernel_evidence["poison_leg"]["domain_faults"] == 0
    )
    if not ok:
        raise RuntimeError(f"config 16 evidence failed: {kernel_evidence}")
    return {
        "metric": "cicids2017_kernel_tier_serving_rows_per_s",
        "_datasets": (train, test),
        "value": kern_r["rows_per_s"], "unit": "rows/s",
        "quality": {
            "micro_batches": kern_r["batches"],
            "kernel_forge": kernel_evidence,
        },
        "n_rows": kern_r["rows"],
    }


# --- config 17: mesh-substrate evidence (r22) -------------------------------
# Four legs.  (A) serving parity: the SAME config-6 deep fused stream
# (minmax -> DCT -> PCA -> LR) served three ways — direct (the pre-r22
# single-device path), substrate at serve mesh 1 (pinned >= 0.95x of
# direct: the substrate costs nothing at one device), and serve-mesh
# sharded across every device (sink bitwise vs direct, soft 0.8x floor
# only: faked devices share this host's cores, so sharding can only
# add dispatch overhead here) — zero recompiles after warmup anywhere.
# (B) flagship fit: the config-2 MLP pipeline fit at mesh 1 and at the
# full mesh — same macro-F1 (the wall-clock parity vs HEAD is read off
# bench_runs.jsonl, config 2 re-journaled on the substrate vs its
# pre-substrate entries).  (C) scaling sweep: one KMeans Lloyd fit per
# mesh size {1,2,4,8} with the sntc_collective_* deltas journaled — the
# wire-bytes series (2*(n-1)*payload per dispatch) must be 0 at mesh 1
# and strictly monotone above it, and every mesh size must produce the
# same centers (the substrate's equivalence contract, measured at bench
# scale).  Faked-CPU devices make THROUGHPUT scaling meaningless (8
# "devices" share the same cores), so the honest monotone pin is the
# collective-bytes series, not rows/s.  (D) chaos: one mesh participant
# dies mid-ALS-fit (the one estimator that dispatches the aggregate per
# iteration) — the collective layer must journal a mesh_resize, the
# survivors must converge, the host never degrades, and zero tenant
# strikes land anywhere in the registry.
BENCH17_REPS = 3
BENCH17_MESH_SIZES = (1, 2, 4, 8)
BENCH17_KMEANS_K = 8


def bench_config17(n_rows, mesh):
    """Mesh-substrate serving throughput (rows/s, serve mesh engine)
    plus the parity/scaling/chaos evidence — the r22 mesh substrate
    measured, not asserted."""
    import shutil
    import tempfile

    import jax

    from sntc_tpu.core.base import Pipeline, PipelineModel
    from sntc_tpu.core.frame import Frame
    from sntc_tpu.feature import DCT, MinMaxScaler, PCA
    from sntc_tpu.fuse import compile_pipeline, fused_segments
    from sntc_tpu.models import (
        ALS,
        KMeans,
        LogisticRegression,
        MultilayerPerceptronClassifier,
    )
    from sntc_tpu.obs.metrics import registry
    from sntc_tpu.parallel import default_mesh
    from sntc_tpu.parallel.collectives import set_collective_domain
    from sntc_tpu.parallel.context import reset_serve_mesh, set_serve_mesh
    from sntc_tpu.parallel.mesh import record_mesh_shape
    from sntc_tpu.resilience import faults as _faults
    from sntc_tpu.resilience.device import DeviceFaultDomain
    from sntc_tpu.serve import (
        BatchPredictor,
        CsvDirSink,
        FileStreamSource,
        StreamingQuery,
    )

    avail = jax.device_count()
    sizes = [n for n in BENCH17_MESH_SIZES if n <= avail]
    if len(sizes) < 2:
        raise RuntimeError(
            "config 17 needs >=2 devices for the scaling/chaos legs; "
            "run with XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(and --platform cpu) on a single-device host"
        )
    mesh_n = max(sizes)

    def _counter_total(snap, name):
        entry = snap.get(name)
        if not entry:
            return 0.0
        return float(
            sum(r.get("value", 0.0) for r in entry["series"])
        )

    train, test = _dataset(n_rows, binary=True)
    # the config-6 serve harness: the DEEP fused pipeline (minmax ->
    # DCT -> PCA -> LR), so the serve mesh shards the fused feature
    # math too, not just the classifier head
    pipe = Pipeline(stages=_feature_stages(mesh, with_scaler=False) + [
        MinMaxScaler(inputCol="rawFeatures", outputCol="mm"),
        DCT(inputCol="mm", outputCol="dct"),
        PCA(mesh=mesh, inputCol="dct", outputCol="features",
            k=BENCH6_PCA_K),
        LogisticRegression(mesh=mesh, maxIter=20),
    ]).fit(train)
    serve_model = PipelineModel(stages=pipe.getStages()[1:])
    features = PipelineModel(
        stages=pipe.getStages()[1:5]  # assemble..PCA -> "features"
    ).transform(train)

    def make_engine(tmp, name, in_dir, chunk_sizes, serve_mesh):
        """Compile + warm one engine UNDER its serve-mesh setting (the
        dispatch-row placement is part of the traced signature, so each
        engine owns its predictor and its compile ledger)."""
        set_serve_mesh(serve_mesh)
        model = compile_pipeline(serve_model)
        predictor = BatchPredictor(model, bucket_rows=BENCH5_SHAPE_BUCKETS)
        warm = StreamingQuery(
            predictor, FileStreamSource(in_dir),
            CsvDirSink(os.path.join(tmp, f"warm_{name}"), durable=False),
            os.path.join(tmp, f"warmckpt_{name}"),
            max_batch_offsets=1, wal_mode="append",
        )
        warm._run_one_batch()
        warm.stop()
        for c in sorted(set(chunk_sizes)):
            predictor.predict_frame(test.slice(0, c))
        segs = fused_segments(model)
        return {"name": name, "serve_mesh": serve_mesh,
                "predictor": predictor, "segments": segs,
                "compiles_before": sum(s.compile_events for s in segs),
                "reps": []}

    def run_once(tmp, eng, in_dir, rep, stream_rows, n_files):
        set_serve_mesh(eng["serve_mesh"])
        name = eng["name"]
        out_dir = os.path.join(tmp, f"out_{name}_{rep}")
        q = StreamingQuery(
            eng["predictor"], FileStreamSource(in_dir),
            CsvDirSink(out_dir, durable=False),
            os.path.join(tmp, f"ckpt_{name}_{rep}"),
            max_batch_offsets=1, wal_mode="append",
            pipeline_depth=1,  # serial engines: the ratio is pure mesh
        )
        t0 = time.perf_counter()
        n_done = q.process_available()
        dt = time.perf_counter() - t0
        rows = (
            stream_rows
            if n_done == n_files
            else sum(p["numInputRows"] for p in q.recentProgress)
        )
        q.stop()
        eng["reps"].append({
            "out_dir": out_dir, "batches": n_done, "rows": rows,
            "dt": dt, "rows_per_s": rows / dt,
        })

    def median_rep(eng):
        reps = sorted(eng["reps"], key=lambda r: r["rows_per_s"])
        rec = dict(reps[len(reps) // 2])
        rec["best_rows_per_s"] = round(reps[-1]["rows_per_s"], 1)
        return rec

    tmp = tempfile.mkdtemp()
    saved_env = {
        k: os.environ.get(k)
        for k in ("SNTC_SERVE_HOST_ROWS", "SNTC_SERVE_MESH_DEVICES")
    }
    os.environ["SNTC_SERVE_HOST_ROWS"] = "0"  # device path both sides
    os.environ.pop("SNTC_SERVE_MESH_DEVICES", None)
    strikes_before = _counter_total(
        registry().snapshot(), "sntc_tenant_strikes_total"
    )
    try:
        # ---- leg A: serving parity under the serve mesh ----
        in_dir = os.path.join(tmp, "in")
        chunk_sizes = _write_bench5_stream(
            in_dir, test, passes=BENCH5_STREAM_PASSES
        )
        stream_rows, n_files = sum(chunk_sizes), len(chunk_sizes)
        engines = [
            make_engine(tmp, "direct", in_dir, chunk_sizes, None),
            make_engine(
                tmp, "mesh1", in_dir, chunk_sizes, default_mesh(1)
            ),
            make_engine(
                tmp, "mesh", in_dir, chunk_sizes, default_mesh(mesh_n)
            ),
        ]
        # rotate the engine order every rep (latin square with
        # BENCH17_REPS == len(engines)): the host slows measurably over
        # a sweep, and a fixed order would charge that drift entirely
        # to whichever engine runs last
        for rep in range(BENCH17_REPS):
            k = rep % len(engines)
            for eng in engines[k:] + engines[:k]:
                run_once(tmp, eng, in_dir, rep, stream_rows, n_files)
        reset_serve_mesh()
        direct_r, mesh1_r, mesh_r = (median_rep(e) for e in engines)
        sink_match = _sinks_match(
            _read_sink_dir(direct_r["out_dir"]),
            _read_sink_dir(mesh_r["out_dir"]),
        ) and _sinks_match(
            _read_sink_dir(direct_r["out_dir"]),
            _read_sink_dir(mesh1_r["out_dir"]),
        )
        recompiles = sum(
            sum(s.compile_events for s in e["segments"])
            - e["compiles_before"]
            for e in engines
        )

        # ---- leg B: flagship fit, mesh 1 vs the full mesh — the
        # substrate's single-device path carries the config-2 workload
        # at the same quality as the sharded one (the wall-clock parity
        # vs HEAD lives in bench_runs.jsonl: config 2 re-journaled on
        # the substrate vs its pre-substrate entries) ----
        mtrain, mtest = _dataset(n_rows)
        flagship = {}
        for n in (1, mesh_n):
            fmesh = default_mesh(n)

            def build(fmesh=fmesh):
                return Pipeline(stages=_feature_stages(fmesh) + [
                    MultilayerPerceptronClassifier(
                        mesh=fmesh, layers=MLP_LAYERS,
                        maxIter=MLP_MAX_ITER, seed=0,
                    )
                ])

            fm, fwarm, fcold = _timed_fit(build, mtrain)
            flagship[f"mesh{n}"] = {
                "warm_s": round(fwarm, 3), "cold_s": round(fcold, 3),
                "macro_f1": round(_evaluate(fm, mtest, fmesh), 4),
            }
        flagship_f1_delta = abs(
            flagship["mesh1"]["macro_f1"]
            - flagship[f"mesh{mesh_n}"]["macro_f1"]
        )

        # ---- leg C: mesh-size sweep + the collective-bytes series ----
        feat = Frame({"features": features["features"]})
        scaling, centers_by_n = [], {}
        for n in sizes:
            snap = registry().snapshot()
            d0 = _counter_total(snap, "sntc_collective_dispatches_total")
            b0 = _counter_total(snap, "sntc_collective_bytes_moved_total")
            t0 = time.perf_counter()
            km = KMeans(
                mesh=default_mesh(n), k=BENCH17_KMEANS_K,
                maxIter=20, seed=0,
            ).fit(feat)
            fit_s = time.perf_counter() - t0
            snap = registry().snapshot()
            centers_by_n[n] = np.asarray(km.clusterCenters, np.float64)
            scaling.append({
                "mesh": n, "fit_s": round(fit_s, 3),
                "collective_dispatches": _counter_total(
                    snap, "sntc_collective_dispatches_total") - d0,
                "collective_bytes": _counter_total(
                    snap, "sntc_collective_bytes_moved_total") - b0,
            })
        ref = centers_by_n[sizes[0]]
        for rec, n in zip(scaling, sizes):
            rec["max_center_diff_vs_mesh1"] = float(
                np.max(np.abs(centers_by_n[n] - ref))
            )
        byte_series = [r["collective_bytes"] for r in scaling]
        bytes_monotone = byte_series[0] == 0 and all(
            b > a for a, b in zip(byte_series[1:], byte_series[2:])
        ) and (len(byte_series) < 2 or byte_series[1] > 0)

        # ---- leg D: chaos — kill one mesh participant mid-fit ----
        rng = np.random.default_rng(0)
        n_u, n_i, rank = 40, 30, 3
        U = rng.normal(size=(n_u, rank)) / np.sqrt(rank)
        V = rng.normal(size=(n_i, rank)) / np.sqrt(rank)
        full = U @ V.T + 2.0
        mask = rng.random((n_u, n_i)) < 0.6
        uu, ii = np.nonzero(mask)
        ratings = Frame({
            "user": uu.astype(np.int64), "item": ii.astype(np.int64),
            "rating": full[uu, ii].astype(np.float32),
        })
        dom = DeviceFaultDomain(probe_async=False)
        set_collective_domain(dom)
        _faults.arm(
            "collective.dispatch", kind="device_lost", after=3, times=1
        )
        try:
            als = ALS(
                mesh=default_mesh(mesh_n), rank=4, maxIter=10,
                regParam=0.02, seed=2,
            ).fit(ratings)
        finally:
            _faults.clear()
            set_collective_domain(None)
        pred = np.asarray(
            als.transform(Frame({"user": uu, "item": ii}))["prediction"]
        )
        rmse = float(np.sqrt(np.mean((pred - full[uu, ii]) ** 2)))
        resizes = [
            r for r in dom.journal if r.get("decision") == "mesh_resize"
        ]
        # gauge read BEFORE the reference fit below — building its
        # aggregate re-records the full mesh shape
        survivors = float(
            registry().get("sntc_collective_mesh_devices", axis="data")
            or 0
        )
        # unfaulted reference, same params on the full mesh: the
        # survivors' result must match its quality, not merely converge
        als_ref = ALS(
            mesh=default_mesh(mesh_n), rank=4, maxIter=10,
            regParam=0.02, seed=2,
        ).fit(ratings)
        pred_ref = np.asarray(
            als_ref.transform(
                Frame({"user": uu, "item": ii})
            )["prediction"]
        )
        rmse_ref = float(
            np.sqrt(np.mean((pred_ref - full[uu, ii]) ** 2))
        )
        record_mesh_shape(default_mesh(mesh_n))  # gauge back to full
        strikes = _counter_total(
            registry().snapshot(), "sntc_tenant_strikes_total"
        ) - strikes_before
    finally:
        reset_serve_mesh()
        _faults.clear()
        set_collective_domain(None)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)

    mesh_evidence = {
        "devices": avail,
        "serve_mesh_devices": mesh_n,
        # mesh-1 substrate vs the direct path: the "no regression at
        # one device" pin (>= 0.95x)
        "serve_mesh1_parity_vs_direct": _round_ratio(
            mesh1_r["rows_per_s"] / direct_r["rows_per_s"]
        ),
        # full-mesh sharded dispatch vs direct: REPORTED with a soft
        # floor only — the faked devices share this host's cores, so
        # sharding can only add overhead here, never parallel speedup
        "serve_sharded_vs_direct": _round_ratio(
            mesh_r["rows_per_s"] / direct_r["rows_per_s"]
        ),
        "direct_rows_per_s": round(direct_r["rows_per_s"], 1),
        "mesh1_rows_per_s": round(mesh1_r["rows_per_s"], 1),
        "best_rows_per_s": mesh_r["best_rows_per_s"],
        "direct_best_rows_per_s": direct_r["best_rows_per_s"],
        "sink_match": sink_match,  # bitwise, end to end
        "recompiles_after_warmup": recompiles,
        "flagship_fit": dict(flagship, f1_delta=flagship_f1_delta),
        "scaling": scaling,
        "collective_bytes_monotone": bytes_monotone,
        "reps": BENCH17_REPS,
        "chaos": {
            "site": "collective.dispatch", "kind": "device_lost",
            "decisions": [
                {k: r[k] for k in ("decision", "from", "to", "site")}
                for r in resizes
            ],
            "mesh_devices_after": survivors,
            "rmse": round(rmse, 4),
            "rmse_unfaulted_ref": round(rmse_ref, 4),
            "host_degraded": dom.host_degraded,
            "tenant_strikes": strikes,
        },
    }
    ok = (
        sink_match
        and mesh_evidence["serve_mesh1_parity_vs_direct"] >= 0.95
        and mesh_evidence["serve_sharded_vs_direct"] >= 0.8
        and recompiles == 0
        # quality parity, not numeric equality: 100 LBFGS iterations on
        # a nonconvex MLP amplify f32 psum reassociation into a
        # different (equally good) optimum — the STEP-level equivalence
        # is pinned at 1e-5 in tests/test_mesh.py, the fit-level pin
        # here is macro-F1 parity
        and flagship_f1_delta <= 0.02
        and bytes_monotone
        and all(r["collective_dispatches"] == 1 for r in scaling)
        and all(
            r["max_center_diff_vs_mesh1"] < 1e-3 for r in scaling
        )
        and len(resizes) == 1
        and resizes[0]["to"] < resizes[0]["from"] == mesh_n
        and survivors == resizes[0]["to"]
        and rmse < 0.1
        and rmse <= rmse_ref + 0.02
        and not dom.host_degraded
        and strikes == 0
    )
    if not ok:
        raise RuntimeError(f"config 17 evidence failed: {mesh_evidence}")
    return {
        "metric": "cicids2017_mesh_substrate_serving_rows_per_s",
        "_datasets": (train, test),
        "value": mesh_r["rows_per_s"], "unit": "rows/s",
        "quality": {
            "micro_batches": mesh_r["batches"],
            "mesh_substrate": mesh_evidence,
        },
        "n_rows": mesh_r["rows"],
    }


# config 18: the disaster-recovery drill (r23).  Configs 12/14 proved
# the process can die and restart on the SAME disk; this one takes the
# disk.  A replicated serve is SIGKILLed mid-stream, the warm standby
# promotes (verify -> truncate-to-barrier -> publish), and a fresh
# engine resumes ON THE PROMOTED TREE to finish the arc — pinned
# bitwise against an unfailed reference, with RPO/RTO and the
# loss-accounting law (committed == through_barrier + tail_loss)
# journaled as the headline evidence.
BENCH18_PHASE_FILES = (6, 6)  # pre-kill, post-promotion


def bench_config18(n_rows, mesh):
    """Warm-standby promotion drill vs an unfailed reference
    (docs/RESILIENCE.md "Disaster recovery")."""
    import shutil
    import signal as _signal
    import subprocess
    import tempfile

    import pyarrow.csv as pacsv

    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.mlio import save_model
    from sntc_tpu.models import LogisticRegression
    from sntc_tpu.resilience.replicate import promote_standby

    train, test = _dataset(n_rows, binary=True)
    pipe = Pipeline(stages=_feature_stages(mesh) + [
        LogisticRegression(mesh=mesh, maxIter=20)
    ]).fit(train)

    n_files = sum(BENCH18_PHASE_FILES)
    chunk = max(96, min(512, n_rows // 120))
    tmp = tempfile.mkdtemp()
    try:
        model_dir = os.path.join(tmp, "model")
        save_model(pipe, model_dir)
        # stage every input file ONCE: both arms serve identical bytes
        staging = os.path.join(tmp, "staging")
        os.makedirs(staging)
        for fi in range(n_files):
            at = (fi * 131) % max(1, test.num_rows - chunk)
            part = test.slice(at, at + chunk)
            pacsv.write_csv(
                part.select(CICIDS2017_FEATURES).to_arrow(),
                os.path.join(staging, f"part_{fi:03d}.csv"),
            )

        def _feed(watch, lo, hi):
            for fi in range(lo, hi):
                name = f"part_{fi:03d}.csv"
                dst = os.path.join(watch, name)
                shutil.copy(os.path.join(staging, name), dst + ".tmp")
                os.rename(dst + ".tmp", dst)

        def _sink_files(out):
            return {
                os.path.basename(p): open(p, "rb").read()
                for p in glob.glob(os.path.join(out, "batch_*.csv"))
            }

        def _argv(watch, out, ckpt, extra):
            return [
                sys.executable, "-m", "sntc_tpu", "serve",
                "--model", model_dir, "--watch", watch, "--out", out,
                "--checkpoint", ckpt, "--max-files-per-batch", "1",
                "--poll-interval", "0.05", "--no-device-faults",
            ] + extra

        # -- the unfailed reference: all files, one --once pass -------
        ref_watch = os.path.join(tmp, "ref", "in")
        ref_out = os.path.join(tmp, "ref", "out")
        os.makedirs(ref_watch)
        _feed(ref_watch, 0, n_files)
        rc_ref = subprocess.run(
            _argv(ref_watch, ref_out, os.path.join(tmp, "ref", "ckpt"),
                  ["--once"]),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode
        ref_sink = _sink_files(ref_out)

        # -- the disaster: a replicated serve, SIGKILLed mid-stream ---
        watch = os.path.join(tmp, "pri", "in")
        out = os.path.join(tmp, "pri", "out")
        ckpt = os.path.join(tmp, "pri", "ckpt")
        standby = os.path.join(tmp, "standby")
        os.makedirs(watch)
        _feed(watch, 0, BENCH18_PHASE_FILES[0])
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            _argv(watch, out, ckpt, ["--standby-root", standby]),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

        def _wait(pred, what, timeout=600.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"config 18: primary exited rc={proc.returncode} "
                        f"waiting for {what}"
                    )
                time.sleep(0.05)
            raise RuntimeError(f"config 18: timed out waiting for {what}")

        _wait(
            lambda: len(_sink_files(out)) >= BENCH18_PHASE_FILES[0],
            "the pre-kill phase to commit",
        )
        rows_mid = sum(
            max(0, b.count(b"\n") - 1) for b in _sink_files(out).values()
        )
        t_mid = time.perf_counter()
        _feed(watch, BENCH18_PHASE_FILES[0], n_files)
        # the kill lands wherever the stream happens to be — committed
        # state past the last barrier is exactly what the law must count
        _wait(
            lambda: len(_sink_files(out)) > BENCH18_PHASE_FILES[0],
            "the disaster window to open",
        )
        proc.send_signal(_signal.SIGKILL)
        proc.wait()

        # -- promote the standby: verify, truncate to barrier, publish
        pro_ckpt = os.path.join(tmp, "promoted", "ckpt")
        pro_out = os.path.join(tmp, "promoted", "out")
        report = promote_standby(
            standby, "default", pro_ckpt, dest_sink=pro_out,
            primary_root=ckpt, primary_sink=out,
        )
        through = int(report.get("batches_through") or 0)
        pro_sink = _sink_files(pro_out)
        promoted_bitwise = bool(through) and all(
            pro_sink.get(f"batch_{i:06d}.csv")
            == ref_sink.get(f"batch_{i:06d}.csv")
            for i in range(through)
        )

        # -- resume ON the promoted tree and finish the arc -----------
        t_resume = time.perf_counter()
        rc_resume = subprocess.run(
            _argv(watch, pro_out, pro_ckpt, ["--once"]),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode
        resume_s = time.perf_counter() - t_resume
        final_sink = _sink_files(pro_out)
        rows_final = sum(
            max(0, b.count(b"\n") - 1) for b in final_sink.values()
        )

        dr_evidence = {
            "stream_files": n_files,
            "killed_after_batches": int(report.get("committed_primary")
                                        or 0),
            "promotion_ok": bool(report.get("ok")),
            "batches_through_barrier": through,
            "rpo_batches": int(report.get("tail_loss_batches") or 0),
            "rpo_rows": int(report.get("tail_loss_rows") or 0),
            "rpo_bytes": int(report.get("rpo_bytes") or 0),
            "rpo_seconds": round(float(report.get("rpo_seconds") or 0.0),
                                 3),
            "rto_seconds": round(float(report.get("rto_seconds") or 0.0),
                                 3),
            "law_exact": bool(report.get("law_exact")),
            "quarantined": len(report.get("quarantined") or ()),
            # the headline invariants: the promoted tree is bitwise the
            # reference up to the barrier, and the resumed arc finishes
            # bitwise identical to the arc that never failed
            "promoted_sink_bitwise": promoted_bitwise,
            "final_sink_bitwise": final_sink == ref_sink,
            "resume_s": round(resume_s, 2),
        }
        ok = (
            rc_ref == 0 and rc_resume == 0
            and dr_evidence["promotion_ok"]
            and dr_evidence["law_exact"]
            and dr_evidence["promoted_sink_bitwise"]
            and dr_evidence["final_sink_bitwise"]
        )
        if not ok:
            raise RuntimeError(
                f"config 18 evidence failed: {dr_evidence} "
                f"(rc_ref={rc_ref}, rc_resume={rc_resume})"
            )
        total_rows = rows_final
        value = (rows_final - rows_mid) / max(
            1e-9, (time.perf_counter() - t_mid)
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "cicids2017_dr_promotion_drill_rows_per_s",
        "_datasets": (train, test),
        "value": round(value, 1), "unit": "rows/s",
        "quality": {"disaster_recovery": dr_evidence},
        "n_rows": total_rows,
    }


BENCHES = {
    "1": bench_config1,
    "2": bench_config2,
    "3": bench_config3,
    "4": bench_config4,
    "5": bench_config5,
    "6": bench_config6,
    "7": bench_config7,
    "8": bench_config8,
    "9": bench_config9,
    "10": bench_config10,
    "11": bench_config11,
    "12": bench_config12,
    "13": bench_config13,
    "14": bench_config14,
    "15": bench_config15,
    "16": bench_config16,
    "17": bench_config17,
    "18": bench_config18,
}


# ---------------------------------------------------------------------------
# --families: comparative wall-clocks for the breadth families (KMeans /
# GaussianMixture / LDA vs their sklearn equivalents on this host; ALS
# has no sklearn analog and reports ours alone).  One JSON line per
# family, journaled like the configs — the evidence that the beyond-
# survey estimators are not just present but fast.
# ---------------------------------------------------------------------------

def bench_families(rows, mesh):
    import jax

    rng = np.random.default_rng(SEED)
    platform = jax.devices()[0].platform
    lines = []

    def emit(name, ours_cold, ours_warm, sk_s, quality):
        line = {
            "metric": f"{name}_fit_wall_clock",
            "value": round(ours_warm, 3),
            "unit": "s",
            "vs_baseline": (
                round(sk_s / ours_warm, 2) if sk_s is not None else None
            ),
            "cold_value": round(ours_cold, 3),
            "sklearn_s": round(sk_s, 3) if sk_s is not None else None,
            "platform": platform,
            "baseline": (
                "sklearn (same host, 1 core)" if sk_s is not None else None
            ),
            **quality,
        }
        lines.append(line)
        return line

    def timed(fn):
        t0 = time.perf_counter()
        out = fn()
        return out, time.perf_counter() - t0

    # ---- KMeans: 200k x 78 flow-shaped rows, k=8 ---------------------------
    from sklearn.cluster import KMeans as SkKMeans

    from sntc_tpu.core.frame import Frame
    from sntc_tpu.models import KMeans

    n_km = min(rows, 200_000)
    Xk = rng.lognormal(0.5, 1.2, size=(n_km, 78)).astype(np.float32)
    fk = Frame({"features": Xk})

    def fit_km():
        return KMeans(mesh=mesh, k=8, maxIter=20, seed=SEED).fit(fk)

    m_cold, t_cold = timed(fit_km)
    m_warm, t_warm = timed(fit_km)
    Xk64 = Xk.astype(np.float64)  # outside the timer: dtype conversion
    # is not model fitting (ours gets a pre-built Frame too)
    sk, t_sk = timed(
        lambda: SkKMeans(
            n_clusters=8, n_init=1, max_iter=20, random_state=SEED,
            algorithm="lloyd",
        ).fit(Xk64)
    )
    emit(
        "kmeans_200k", t_cold, t_warm, t_sk,
        {
            "n_rows": n_km,
            "inertia_ratio": round(
                m_warm.summary.trainingCost / max(sk.inertia_, 1e-9), 4
            ),
        },
    )

    # ---- GaussianMixture: 50k x 20, k=5 full covariance --------------------
    from sklearn.mixture import GaussianMixture as SkGMM

    from sntc_tpu.models import GaussianMixture

    n_gm = min(rows, 50_000)
    centers = rng.normal(size=(5, 20)) * 4
    Xg = (
        centers[rng.integers(0, 5, n_gm)]
        + rng.normal(size=(n_gm, 20))
    ).astype(np.float32)
    fg = Frame({"features": Xg})

    def fit_gm():
        return GaussianMixture(k=5, maxIter=30, seed=SEED, tol=1e-3).fit(fg)

    g_cold, t_cold = timed(fit_gm)
    g_warm, t_warm = timed(fit_gm)
    Xg64 = Xg.astype(np.float64)
    sk_g, t_sk = timed(
        lambda: SkGMM(
            n_components=5, covariance_type="full", max_iter=30,
            tol=1e-3, n_init=1, random_state=SEED,
        ).fit(Xg64)
    )
    emit(
        "gmm_50k", t_cold, t_warm, t_sk,
        {
            "n_rows": n_gm,
            # summary.logLikelihood is already the weighted MEAN
            # (gaussian_mixture.py e_step) — directly comparable to
            # sklearn's .score()
            "our_mean_ll": round(float(g_warm.summary.logLikelihood), 4),
            "sk_mean_ll": round(float(sk_g.score(Xg64)), 4),
        },
    )

    # ---- LDA: 5k docs x 1k vocab, k=10 online VB ---------------------------
    from sklearn.decomposition import LatentDirichletAllocation as SkLDA

    from sntc_tpu.models import LDA

    n_docs, vocab, k_t = min(rows // 40, 5_000), 1_000, 10
    beta = rng.dirichlet([0.05] * vocab, size=k_t)
    theta = rng.dirichlet([0.3] * k_t, size=n_docs)
    Xl = np.zeros((n_docs, vocab), np.float32)
    for d0 in range(0, n_docs, 1_000):
        d1 = min(d0 + 1_000, n_docs)
        probs = theta[d0:d1] @ beta
        Xl[d0:d1] = np.stack(
            [rng.multinomial(120, probs[i]) for i in range(d1 - d0)]
        )
    fl = Frame({"features": Xl})

    # ours: 20 minibatches of 10% ≈ sklearn's 2 online epochs (batch 500)
    def fit_lda():
        return LDA(
            mesh=mesh, k=k_t, maxIter=20, subsamplingRate=0.1, seed=SEED,
        ).fit(fl)

    _, t_cold = timed(fit_lda)
    l_warm, t_warm = timed(fit_lda)
    Xl64 = Xl.astype(np.float64)
    sk_l, t_sk = timed(
        lambda: SkLDA(
            n_components=k_t, learning_method="online", batch_size=500,
            max_iter=2, random_state=SEED,
        ).fit(Xl64)
    )
    emit(
        "lda_5k_online", t_cold, t_warm, t_sk,
        {
            "n_rows": n_docs,
            "our_log_perplexity": round(l_warm.logPerplexity(fl), 4),
            "sk_log_perplexity": round(
                float(np.log(sk_l.perplexity(Xl64))), 4
            ),
        },
    )

    # ---- ALS: 500k implicit ratings, rank 16 (no sklearn analog) -----------
    from sntc_tpu.models import ALS

    n_r = 500_000  # fixed workload — not scaled by --rows (the other
    # families use rows; ALS cost scales with ratings, not matrix rows)
    users = rng.integers(0, 20_000, n_r)
    items = rng.integers(0, 2_000, n_r)
    ratings = rng.integers(1, 6, n_r).astype(np.float32)
    fa = Frame({"user": users, "item": items, "rating": ratings})

    def fit_als():
        return ALS(
            mesh=mesh, rank=16, maxIter=5, regParam=0.05,
            implicitPrefs=True, seed=SEED,
        ).fit(fa)

    a_cold, t_cold = timed(fit_als)
    _, t_warm = timed(fit_als)
    emit(
        f"als_{n_r // 1000}k_implicit_r16", t_cold, t_warm, None,
        {"n_rows": n_r, "n_users": 20_000, "n_items": 2_000},
    )
    return lines


# ---------------------------------------------------------------------------
# --mfu: absolute utilization accounting (VERDICT r2 item 3) — answers
# "actually fast?" independently of the 1-core sklearn proxy
# ---------------------------------------------------------------------------

# Peak FLOP/s comes from the shared probe table
# (sntc_tpu.utils.backend_probe.probed_peaks — TPU v5e 197 TFLOP/s bf16
# public spec; f32 matmuls under JAX's DEFAULT precision also feed the
# MXU bf16 inputs with f32 accumulate, so the same peak applies to both
# computeDtype settings; CPU gets an honest "estimate"-labeled figure).
# BENCH_PEAK_FLOPS keeps its historical override precedence, then the
# probe's own SNTC_PEAK_FLOPS.


def _peak_flops(platform: str):
    """(peak_flops_per_s, peak_source) for this platform."""
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env), "env"
    from sntc_tpu.utils.backend_probe import probed_peaks

    peaks = probed_peaks(platform)
    return peaks["flops"], peaks["peak_source"]


def bench_mfu(n_rows, mesh):
    """Measured FLOP/s vs chip peak for the two compute cores:

    (a) the flagship MLP LBFGS fit (configs 2): analytic gemm FLOPs —
        fwd 2·N·Σ(fan_in·fan_out), bwd 2× that — ONE fused
        value-and-grad eval per LBFGS iteration (the line search
        carries the candidate gradient since `0218f3a`; a LOWER bound
        when backtracking re-evals), over the measured warm fit; run
        at BOTH computeDtype settings, so the bf16-vs-f32 claim
        (mlp.py) is measured, not asserted;
    (b) the Pallas one-hot histogram kernel at config-3 level-pass
        shapes (classification stats S=15, the widest node width the
        kernel's VMEM gate admits — the same shrink the grower
        applies): executed (padded) one-hot-matmul FLOPs over measured
        kernel time — MXU-bound or not, in absolute terms.
    """
    import jax
    import jax.numpy as jnp

    from sntc_tpu.models import MultilayerPerceptronClassifier

    platform = jax.devices()[0].platform
    peak, peak_source = _peak_flops(platform)
    train, _ = _dataset(n_rows)
    out = {"metric": "mfu_accounting", "n_rows": None, "unit": "mfu",
           "platform": platform, "peak_flops": peak,
           "peak_source": peak_source}

    # ---- (a) MLP fit at f32 and bf16 ----
    stages = _feature_stages(mesh)
    feat = train
    for st in stages:
        fitted = st.fit(feat) if hasattr(st, "fit") else st
        feat = fitted.transform(feat)
    N = feat.num_rows
    out["n_rows"] = N
    gemm_macs = sum(
        a * b for a, b in zip(MLP_LAYERS[:-1], MLP_LAYERS[1:])
    )
    flops_per_eval = 6.0 * N * gemm_macs  # fwd 2x + bwd 4x MACs
    for dtype in ("float32", "bfloat16"):
        def build():
            return MultilayerPerceptronClassifier(
                mesh=mesh, layers=MLP_LAYERS, maxIter=MLP_MAX_ITER,
                seed=0, computeDtype=dtype,
            )

        model, warm, cold = _timed_fit(build, feat)
        iters = model.summary.totalIterations
        # one fused fwd+bwd per iteration at the typical immediate
        # line-search accept (exact since the gradient-carry change;
        # backtracking re-evals only add FLOPs, so MFU is a lower bound)
        total_flops = flops_per_eval * float(iters)
        key = "f32" if dtype == "float32" else "bf16"
        out[f"mlp_{key}_fit_s"] = round(warm, 4)
        out[f"mlp_{key}_iters"] = iters
        out[f"mlp_{key}_flops_per_s"] = total_flops / warm
        if peak:
            out[f"mlp_{key}_mfu"] = round(total_flops / warm / peak, 5)
    out["bf16_speedup_vs_f32"] = round(
        out["mlp_f32_fit_s"] / out["mlp_bf16_fit_s"], 3
    )

    # ---- (b) histogram kernel at config-3 level shapes ----
    from sntc_tpu.ops.pallas_histogram import (
        hist_fits_pallas,
        level_histogram_pallas,
    )

    from sntc_tpu.models.tree.grower import node_group_size

    F, B, S = CHISQ_TOP, 32, 15  # config-3 classification stats width
    # the width a config-3 level pass really runs: the deepest level,
    # capped by the grower's memory-bounded node group, shrunk until
    # the kernel's VMEM gate admits it — the same resolution
    # grow_forest applies on TPU
    n_nodes = min(
        2 ** (RF_DEPTH - 1), node_group_size(RF_TREES, F, B, S)
    )
    while n_nodes > 1 and not hist_fits_pallas(n_nodes, B):
        n_nodes //= 2
    if hist_fits_pallas(n_nodes, B) and platform != "cpu":
        rng = np.random.default_rng(0)
        n_loc = min(N, 200_000)
        binned_t = jnp.asarray(
            rng.integers(0, B, size=(F, n_loc), dtype=np.int32)
        )
        node_idx = jnp.asarray(
            rng.integers(0, n_nodes, size=n_loc, dtype=np.int32)
        )
        stats = jnp.asarray(rng.random((n_loc, S), np.float32))
        call = jax.jit(
            lambda bt, ni, st: level_histogram_pallas(
                bt, ni, st, n_nodes=n_nodes, n_bins=B
            )
        )
        call(binned_t, node_idx, stats).block_until_ready()  # compile
        reps = 10
        t0 = time.perf_counter()
        for _ in range(reps):
            r = call(binned_t, node_idx, stats)
        r.block_until_ready()
        dt = (time.perf_counter() - t0) / reps
        # executed dense FLOPs: one-hot [tile, nb_pad]ᵀ @ stats
        # [tile, s_pad] per feature block — padded widths are what the
        # MXU really runs
        nb_pad = -(-max(n_nodes * B + 1, 128) // 128) * 128
        s_pad = -(-S // 8) * 8
        hist_flops = 2.0 * n_loc * nb_pad * s_pad * F
        out["hist_kernel_shapes"] = (
            f"N={n_loc} F={F} nodes={n_nodes} bins={B}"
        )
        out["hist_kernel_s"] = round(dt, 5)
        out["hist_flops_per_s"] = hist_flops / dt
        if peak:
            out["hist_mfu"] = round(hist_flops / dt / peak, 5)
    else:
        out["hist_kernel_s"] = None  # pallas path unavailable here

    out["value"] = out.get("mlp_f32_mfu") or out["mlp_f32_flops_per_s"]
    out["vs_baseline"] = None
    return out


# ---------------------------------------------------------------------------
# CPU proxy baselines (sklearn).  Since r5 every config run measures its
# proxy IN THE SAME INVOCATION on the SAME train/test split (the
# --families discipline, VERDICT r4 item 2): host speed drifts by large
# factors across hours on this box, and a ratio of two same-session
# numbers cancels that drift where a cached proxy cannot.  The cache +
# --measure-baseline path remains for --no-pair and for pre-measuring.
# ---------------------------------------------------------------------------


def _proxy_xy(frame, vocab=None):
    """(X, y, vocab): labels encoded against ``vocab`` (built from this
    frame when None).  Rows with labels outside the vocab are DROPPED —
    symmetric with the pipeline under test, whose StringIndexer uses
    handleInvalid='skip'; per-frame np.unique codes would silently
    misalign train vs test whenever their label sets differ."""
    from sntc_tpu.data import CICIDS2017_FEATURES

    X = np.stack([frame[c] for c in CICIDS2017_FEATURES], axis=1)
    labels = frame["Label"].astype(str)
    if vocab is None:
        vocab = np.unique(labels)
    idx = np.searchsorted(vocab, labels)
    idx_c = np.clip(idx, 0, len(vocab) - 1)
    valid = vocab[idx_c] == labels
    return X[valid], idx_c[valid].astype(np.int64), vocab


def proxy_config1(train, test):
    from sklearn.linear_model import LogisticRegression as SkLR
    from sklearn.metrics import roc_auc_score
    from sklearn.preprocessing import StandardScaler as SkScaler

    X, y, vocab = _proxy_xy(train)
    Xt, yt, _ = _proxy_xy(test, vocab)
    t0 = time.perf_counter()
    scaler = SkScaler().fit(X)
    clf = SkLR(max_iter=LR_MAX_ITER, tol=1e-6).fit(scaler.transform(X), y)
    dt = time.perf_counter() - t0
    auc = roc_auc_score(yt, clf.predict_proba(scaler.transform(Xt))[:, 1])
    return {
        "desc": "LogisticRegression lbfgs, standardized",
        "train_s": dt,
        "quality": {"areaUnderROC": float(auc)},
    }


def proxy_config2(train, test):
    from sklearn.metrics import f1_score
    from sklearn.neural_network import MLPClassifier
    from sklearn.preprocessing import StandardScaler as SkScaler

    X, y, vocab = _proxy_xy(train)
    Xt, yt, _ = _proxy_xy(test, vocab)
    t0 = time.perf_counter()
    scaler = SkScaler().fit(X)
    clf = MLPClassifier(
        hidden_layer_sizes=(MLP_LAYERS[1],), activation="logistic",
        solver="lbfgs", max_iter=MLP_MAX_ITER, tol=1e-6, random_state=0,
    ).fit(scaler.transform(X), y)
    dt = time.perf_counter() - t0
    f1 = f1_score(yt, clf.predict(scaler.transform(Xt)), average="macro")
    return {
        "desc": "MLPClassifier 78-64-15 logistic lbfgs 100 iters",
        "train_s": dt,
        "quality": {"macro_f1": float(f1)},
    }


def proxy_config3(train, test):
    from sklearn.ensemble import RandomForestClassifier as SkRF
    from sklearn.feature_selection import SelectKBest, chi2
    from sklearn.metrics import f1_score
    from sklearn.preprocessing import MinMaxScaler

    X, y, vocab = _proxy_xy(train)
    Xt, yt, _ = _proxy_xy(test, vocab)
    t0 = time.perf_counter()
    mm = MinMaxScaler().fit(X)
    sel = SelectKBest(chi2, k=CHISQ_TOP).fit(mm.transform(X), y)
    rf = SkRF(
        n_estimators=RF_TREES, max_depth=RF_DEPTH, n_jobs=-1,
        random_state=0,
    ).fit(sel.transform(mm.transform(X)), y)
    dt = time.perf_counter() - t0
    f1 = f1_score(
        yt, rf.predict(sel.transform(mm.transform(Xt))), average="macro"
    )
    return {
        "desc": f"SelectKBest(chi2,k={CHISQ_TOP}) + RF",
        "train_s": dt,
        "quality": {"macro_f1": float(f1)},
    }


def proxy_config4(train, test):
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.metrics import f1_score
    from sklearn.multiclass import OneVsRestClassifier

    X, y, vocab = _proxy_xy(train)
    Xt, yt, _ = _proxy_xy(test, vocab)
    t0 = time.perf_counter()
    clf = OneVsRestClassifier(
        GradientBoostingClassifier(
            n_estimators=GBT_ROUNDS, max_depth=GBT_DEPTH,
            learning_rate=0.1, random_state=0,
        )
    ).fit(X, y)
    dt = time.perf_counter() - t0
    f1 = f1_score(yt, clf.predict(Xt), average="macro")
    return {
        "desc": f"OneVsRest(GradientBoosting x{GBT_ROUNDS})",
        "train_s": dt,
        "quality": {"macro_f1": float(f1)},
    }


def proxy_config5(train, test):
    """Serving throughput proxy: fit excluded (like ours); the same
    end-to-end job the engine is measured on since r8 — micro-batch CSV
    files stream in, the full enriched row (features + prediction)
    streams out as CSV — with sklearn predict in the middle.  File
    setup is outside the timer, exactly as the engine's input stream
    is."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.csv as pacsv
    from sklearn.linear_model import LogisticRegression as SkLR
    from sklearn.preprocessing import StandardScaler as SkScaler

    X, y, _ = _proxy_xy(train)
    scaler = SkScaler().fit(X)
    clf = SkLR(max_iter=20).fit(scaler.transform(X), y)
    tmp = tempfile.mkdtemp()
    # same arrow intra-op pinning as the engine measurement (see
    # bench_config5) — both sides of the paired ratio parse/write CSV
    # with one intra-op thread
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)
    try:
        chunk_sizes = _write_bench5_stream(tmp, test)
        n_files, n_test = len(chunk_sizes), sum(chunk_sizes)
        paths = sorted(glob.glob(os.path.join(tmp, "part_*.csv")))
        t0 = time.perf_counter()
        for k, p in enumerate(paths):
            table = pacsv.read_csv(p)
            Xc = np.stack(
                [
                    table.column(c).to_numpy()
                    for c in table.column_names
                ],
                axis=1,
            )
            pred = clf.predict(scaler.transform(Xc))
            out = table.append_column(
                "prediction", pa.array(pred.astype(np.float64))
            )
            pacsv.write_csv(out, os.path.join(tmp, f"out_{k:05d}.csv"))
        dt = time.perf_counter() - t0
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "desc": "CSV-in → assemble+scale+predict → enriched-CSV-out, "
                f"{n_files} micro-batch files",
        "rows_per_s": n_test / dt,
        "n_rows_served": int(n_test),
    }


def proxy_config7(train, test):
    """Online-learning proxy for the lifecycle arc: sklearn GaussianNB
    doing the same test-then-train loop over the same micro-batch CSV
    stream — predict each file, write the enriched CSV, then
    ``partial_fit`` on the batch's labels (the sklearn streaming
    recipe).  File setup is outside the timer, like ours."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.csv as pacsv
    from sklearn.naive_bayes import GaussianNB

    # paired path: ``test`` is the bench's list of stream frames;
    # --measure-baseline hands a plain Frame instead — slice it
    if not isinstance(test, list):
        per = max(256, test.num_rows // BENCH7_BATCHES)
        test = [
            test.slice(i, min(i + per, test.num_rows))
            for i in range(0, test.num_rows, per)
        ]
    vocab = sorted(set(str(v) for f in test for v in f["Label"]))
    label_index = {v: i for i, v in enumerate(vocab)}
    feat_cols = [c for c in test[0].columns if c != "Label"]
    Xw = np.stack(
        [np.asarray(train[c], np.float64) for c in feat_cols], axis=1
    )
    yw = np.asarray(
        [label_index.get(str(v), 0) for v in train["Label"]], np.int64
    )
    clf = GaussianNB().fit(Xw, yw)
    tmp = tempfile.mkdtemp()
    arrow_cpus = pa.cpu_count()
    pa.set_cpu_count(1)  # same intra-op pinning as the engine side
    try:
        paths = []
        for i, f in enumerate(test):
            p = os.path.join(tmp, f"part_{i:04d}.csv")
            pacsv.write_csv(f.select(feat_cols + ["Label"]).to_arrow(), p)
            paths.append(p)
        n_rows = sum(f.num_rows for f in test)
        t0 = time.perf_counter()
        for k, p in enumerate(paths):
            table = pacsv.read_csv(p)
            Xc = np.stack(
                [table.column(c).to_numpy() for c in feat_cols], axis=1
            )
            yc = np.asarray(
                [
                    label_index.get(str(v), 0)
                    for v in table.column("Label").to_pylist()
                ],
                np.int64,
            )
            pred = clf.predict(Xc)
            out = table.append_column(
                "prediction", pa.array(pred.astype(np.float64))
            )
            pacsv.write_csv(out, os.path.join(tmp, f"out_{k:05d}.csv"))
            clf.partial_fit(Xc, yc)
        dt = time.perf_counter() - t0
    finally:
        pa.set_cpu_count(arrow_cpus)
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "desc": "CSV-in → predict → enriched-CSV-out → GaussianNB "
                f"partial_fit per batch, {len(paths)} micro-batch files",
        "rows_per_s": n_rows / dt,
        "n_rows_served": int(n_rows),
    }


PROXIES = {
    "1": proxy_config1,
    "2": proxy_config2,
    "3": proxy_config3,
    "4": proxy_config4,
    "5": proxy_config5,
    # config 6 serves the same CSV-in -> predict -> CSV-out job as
    # config 5 (the fused pipeline is deeper, the proxy's job identical)
    "6": proxy_config5,
    "7": proxy_config7,
    # config 8's aggregate is the same job at N-tenant scale; the fair
    # single-process comparison point is the config-5 proxy's CSV ->
    # predict -> CSV rows/s
    "8": proxy_config5,
    # config 9 computes the features live before the same CSV-out job;
    # the proxy stays the precomputed CSV -> predict -> CSV baseline
    "9": proxy_config5,
    # config 10 is the same CSV -> predict -> CSV job with the ingest
    # engine tuning itself; the fair external anchor is unchanged
    "10": proxy_config5,
    # config 11 is the same serving job with the SLO controller
    # steering the knobs; the external anchor stays the config-5 proxy
    "11": proxy_config5,
    # config 12 is the same serving job soaked over many cycles with
    # the storage lifecycle armed; the external anchor is unchanged
    "12": proxy_config5,
    # config 13 is the same serving job with the device-fault storm
    # landing mid-stream; the external anchor stays the config-5 proxy
    "13": proxy_config5,
    # config 14 is the same serving job spread over a worker fleet
    # with one worker killed; the external anchor stays the config-5
    # proxy
    "14": proxy_config5,
    # config 15 is the same serving job fed over a loopback socket
    # through the ingress WAL; the external anchor stays the config-5
    # proxy
    "15": proxy_config5,
    # config 16 is the same CSV -> predict -> CSV job with the serving
    # kernel tier carrying the hot path; the external anchor stays the
    # config-5 proxy
    "16": proxy_config5,
    # config 17 is the same CSV -> predict -> CSV job with the serve
    # mesh sharding dispatch rows; the external anchor stays the
    # config-5 proxy
    "17": proxy_config5,
    # config 18 is the same serving job put through the warm-standby
    # promotion drill; the external anchor stays the config-5 proxy
    "18": proxy_config5,
}


def measure_baseline(configs, rows):
    """Measure the sklearn proxies standalone and cache them — the
    --no-pair fallback and a pre-measured sanity anchor.  Same proxy
    functions the paired path runs in-invocation."""
    cache = {}
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            cache = json.load(f)

    for cfg in configs:
        n = rows or DEFAULT_ROWS[cfg]
        train, test = _dataset(
            n, binary=cfg in ("1", "5", "6", "9", "10", "11", "12")
        )
        p = PROXIES[cfg](train, test)
        entry = {
            "baseline": f"sklearn CPU proxy: {p['desc']}",
            "n_rows": (
                int(test.num_rows)
                if cfg in ("5", "6", "7", "9", "10", "11", "12")
                else int(train.num_rows)
            ),
            "host_cpus": os.cpu_count(),
        }
        for k in ("train_s", "rows_per_s"):
            if k in p:
                entry[k] = p[k]
        if "quality" in p:
            entry["quality"] = p["quality"]
        cache[cfg] = entry
        shown = entry.get("train_s") or entry.get("rows_per_s")
        print(
            f"baseline config {cfg}: {shown:.1f} "
            f"{entry.get('quality', '')}",
            file=sys.stderr,
        )

    with open(BASELINE_CACHE, "w") as f:
        json.dump(cache, f, indent=1)
    return cache


def _load_baseline(cfg: str) -> dict:
    if not os.path.exists(BASELINE_CACHE):
        return {}
    with open(BASELINE_CACHE) as f:
        cache = json.load(f)
    base = cache.get(cfg)
    if base is None and cfg == "2" and "train_s" in cache:
        base = cache  # legacy single-config cache layout
    return base or {}


def _vs_baseline(cfg: str, result: dict, base: dict):
    if not base:
        return None
    if cfg in ("5", "6", "7", "9", "10", "12"):
        return result["value"] / base["rows_per_s"]  # throughput ratio
    scale = result["n_rows"] / max(base["n_rows"], 1)
    return (base["train_s"] * scale) / result["value"]


def _round_ratio(r):
    """3 significant digits: tiny ratios (smoke-scale runs where fixed
    overhead dominates) must not collapse to 0.0."""
    return float(f"{r:.3g}")


def _is_rendezvous_abort(returncode, stderr: str) -> bool:
    """The known XLA:CPU collective flake (VERDICT r5): the child dies
    with SIGABRT (rc -6, or 134 through a shell) and the 'threads to
    join the rendezvous' timeout on stderr.  Only THIS signature is
    retryable — any other nonzero exit is a real failure."""
    if returncode not in (-6, 134):
        return False
    return "rendezvous" in (stderr or "").lower()


def run_config_isolated(cfg: str, args, runner=None) -> dict:
    """Run one config as a child ``bench.py`` process (``--isolate``).

    A crash in one config can no longer kill a full ``--config all``
    sweep, and a child that dies with the collective-rendezvous SIGABRT
    signature is retried EXACTLY once, journaling ``"retried": true`` in
    the bench record so the flake is visible, not silently absorbed.
    The child runs with ``BENCH_NO_JOURNAL=1`` — the parent owns the
    journal entry.  ``runner`` is injectable for tests."""
    import subprocess

    runner = runner or subprocess.run
    cmd = [sys.executable, os.path.abspath(__file__), "--config", cfg]
    if args.rows:
        cmd += ["--rows", str(args.rows)]
    if args.no_pair:
        cmd += ["--no-pair"]
    if args.platform:
        cmd += ["--platform", args.platform]
    env = dict(os.environ, BENCH_NO_JOURNAL="1")
    # the child must NOT inherit isolate mode, or it would recursively
    # re-spawn itself for its single config
    env.pop("BENCH_ISOLATE", None)
    # each child exports its own trace at exit — on the shared parent
    # path successive configs would overwrite each other, so fan the
    # trace out to one file per config
    if env.get("BENCH_TRACE_OUT"):
        base, ext = os.path.splitext(env["BENCH_TRACE_OUT"])
        env["BENCH_TRACE_OUT"] = f"{base}.config{cfg}{ext or '.json'}"
    retried = False
    proc = None
    for attempt in (1, 2):
        proc = runner(cmd, capture_output=True, text=True, env=env)
        if proc.returncode == 0:
            break
        if attempt == 1 and _is_rendezvous_abort(
            proc.returncode, proc.stderr
        ):
            retried = True
            print(
                f"bench: config {cfg} died with the collective-"
                "rendezvous SIGABRT signature; retrying once",
                file=sys.stderr,
            )
            continue
        raise RuntimeError(
            f"bench config {cfg} child failed rc={proc.returncode}"
            + (" (after one rendezvous retry)" if retried else "")
            + f": {(proc.stderr or '')[-2000:]}"
        )
    lines = [
        ln for ln in (proc.stdout or "").splitlines() if ln.startswith("{")
    ]
    if not lines:
        raise RuntimeError(
            f"bench config {cfg} child emitted no JSON line: "
            f"{(proc.stdout or '')[-500:]}"
        )
    line = json.loads(lines[-1])
    if retried:
        line["retried"] = True
    return line


def run_config(cfg: str, rows, pair: bool = True):
    import jax

    from sntc_tpu.obs.trace import span
    from sntc_tpu.parallel.context import get_default_mesh

    mesh = get_default_mesh()
    # phase span (replaces the dormant utils.profiling.StepTimer): one
    # span per config run on the process tracer when BENCH_TRACE_OUT
    # armed it — nested engine/ingest spans land inside it
    with span("bench.config", config=cfg):
        result = BENCHES[cfg](rows or DEFAULT_ROWS[cfg], mesh)
    train, test = result.pop("_datasets", (None, None))
    line = {
        "metric": result["metric"],
        "value": round(result["value"], 3),
        "unit": result.get("unit", "s"),
    }
    if pair:
        # drift-proof ratio: the sklearn proxy runs NOW, in this same
        # invocation, on the same train/test split — both sides of the
        # ratio see the same host state (VERDICT r4 item 2)
        proxy = PROXIES[cfg](train, test)
        if cfg in ("5", "6", "7", "8", "9", "10", "11", "12", "13",
                   "14", "15", "16", "17", "18"):
            line["vs_baseline"] = _round_ratio(
                result["value"] / proxy["rows_per_s"]
            )
            line["proxy_rows_per_s"] = round(proxy["rows_per_s"], 1)
        else:
            line["vs_baseline"] = _round_ratio(
                proxy["train_s"] / result["value"]
            )
            line["proxy_s"] = round(proxy["train_s"], 3)
        line["paired"] = True
        base_quality = proxy.get("quality")
        line["baseline"] = (
            f"sklearn-cpu-proxy same-invocation: {proxy['desc']}"
        )
    else:
        base = _load_baseline(cfg)
        v = _vs_baseline(cfg, result, base)
        line["vs_baseline"] = _round_ratio(v) if v else None
        line["paired"] = False
        base_quality = base.get("quality")
        line["baseline"] = "sklearn-cpu-proxy (baseline_proxy.json)"
    for k in ("cold_value", "n_rows"):
        if k in result:
            line[k] = (
                round(result[k], 3) if isinstance(result[k], float) else result[k]
            )
    line.update(result.get("quality", {}))
    if base_quality:
        line["baseline_quality"] = base_quality
    line["platform"] = jax.devices()[0].platform
    return line




def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="2", choices=list(BENCHES) + ["all"])
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--measure-baseline", action="store_true")
    ap.add_argument(
        "--mfu", action="store_true",
        help="utilization accounting: measured FLOP/s vs chip peak for "
        "the MLP LBFGS fit (f32 AND bf16) + the Pallas histogram kernel",
    )
    ap.add_argument(
        "--families", action="store_true",
        help="comparative wall-clocks for the breadth families (KMeans/"
        "GMM/LDA vs sklearn on this host; ALS ours-only), one JSON "
        "line each",
    )
    ap.add_argument(
        "--no-pair", action="store_true",
        default=bool(os.environ.get("BENCH_NO_PAIR")),
        help="skip the same-invocation sklearn proxy (fall back to the "
        "cached baseline_proxy.json with row scaling; rows journal "
        "paired:false)",
    )
    ap.add_argument(
        "--isolate", action="store_true",
        default=bool(os.environ.get("BENCH_ISOLATE")),
        help="run each config in its own child process: one config's "
        "crash can't kill the sweep, and the known collective-"
        "rendezvous SIGABRT flake is retried exactly once (journaled "
        "as retried:true)",
    )
    ap.add_argument(
        "--platform", default=os.environ.get("BENCH_PLATFORM"),
        help="force a JAX platform (e.g. 'cpu' for local validation when "
        "the TPU tunnel is unavailable); the host sitecustomize pins "
        "jax_platforms so the JAX_PLATFORMS env var alone is ignored",
    )
    args = ap.parse_args()

    configs = list(BENCHES) if args.config == "all" else [args.config]

    if args.measure_baseline:
        # sklearn-only path: no JAX, so no backend probe needed
        cache = measure_baseline(configs, args.rows)
        print(json.dumps({c: cache.get(c) for c in configs}))
        return

    if args.isolate and (args.mfu or args.families):
        print(
            "bench: --isolate only covers --config runs; this "
            "--mfu/--families invocation runs in-process",
            file=sys.stderr,
        )
    if args.isolate and not (args.mfu or args.families):
        # children probe/enable their own backend+cache; the parent
        # stays jax-free so a config crash can never take it down
        ordered = sorted(configs, key=lambda c: (c == "2", c))
        for cfg in ordered:
            line = run_config_isolated(cfg, args)
            _journal_run(cfg, line)
            print(json.dumps(line), flush=True)
        return

    # the TPU tunnel can hang indefinitely inside jax.devices(); a hung
    # bench records nothing — shared probe+fallback policy
    # (sntc_tpu.utils.backend_probe; the "platform" field in the output
    # line shows what really ran; BENCH_PROBE_TIMEOUT_S overrides)
    from sntc_tpu.utils.backend_probe import resolve_platform

    platform = resolve_platform(
        args.platform, specific_env="BENCH_PROBE_TIMEOUT_S"
    )
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from sntc_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    # the metrics plane rides every bench run (each journal record
    # carries its window's registry delta); BENCH_TRACE_OUT=<path>
    # additionally arms the span tracer and exports the whole sweep's
    # host-stage timeline as Chrome-trace JSON at exit
    from sntc_tpu.obs import install_event_metrics

    install_event_metrics()
    if os.environ.get("BENCH_TRACE_OUT"):
        from sntc_tpu.obs import enable_tracing

        enable_tracing()

    if args.mfu:
        from sntc_tpu.parallel.context import get_default_mesh

        line = bench_mfu(
            args.rows or DEFAULT_ROWS["2"], get_default_mesh()
        )
        _journal_run("mfu", line)
        print(json.dumps(line), flush=True)
        return

    if args.families:
        from sntc_tpu.parallel.context import get_default_mesh

        for line in bench_families(
            args.rows or 200_000, get_default_mesh()
        ):
            _journal_run(f"family:{line['metric']}", line)
            print(json.dumps(line), flush=True)
        return

    # flagship (config 2) last so the driver's final line is the headline
    ordered = sorted(configs, key=lambda c: (c == "2", c))
    for cfg in ordered:
        line = run_config(cfg, args.rows, pair=not args.no_pair)
        # evidence in the PRINTED line, not only the journal record: an
        # --isolate child runs with BENCH_NO_JOURNAL=1 and its stdout
        # line is all the parent's journal will ever see of its ring.
        # Guard BEFORE summarizing — the summary advances the event
        # watermark, and discarding it would silently drop events.
        if "resilience" not in line:
            resilience = _resilience_summary()
            if resilience is not None:
                line["resilience"] = resilience
        # same discipline for the registry delta: fold it into the
        # PRINTED line so an --isolate child ships its obs evidence
        # through stdout (the parent's registry never saw its counters)
        if "obs" not in line:
            obs = _obs_summary()
            if obs is not None:
                line["obs"] = obs
        _journal_run(cfg, line)
        print(json.dumps(line), flush=True)

    if os.environ.get("BENCH_TRACE_OUT"):
        from sntc_tpu.obs import tracer

        t = tracer()
        if t is not None:
            t.export_chrome_trace(os.environ["BENCH_TRACE_OUT"])


if __name__ == "__main__":
    main()
