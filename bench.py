"""Benchmark harness — the measurement frame of BASELINE.md.

Metric of record (BASELINE.json:2): CICIDS2017 end-to-end training
wall-clock at macro-F1 parity.  No Spark and no real CICIDS2017 exist
in-image (SURVEY.md §6), so:

  * the workload is the schema-locked synthetic generator (78 features,
    15 labels, benign-heavy priors, Inf/NaN dirt) — real day CSVs drop in
    unchanged when available;
  * the baseline is a CPU proxy (sklearn MLPClassifier, same topology and
    optimizer family, measured on this host via ``--measure-baseline``
    and cached in ``baseline_proxy.json``), clearly labeled as a proxy.

Prints ONE JSON line:
  {"metric": ..., "value": <train_wall_clock_s>, "unit": "s",
   "vs_baseline": <baseline_s / ours_s>}

``value`` is the steady-state fit time (a same-shape warmup fit first, so
XLA compile — a one-off per shape, cached across fits — is excluded; the
cold time is reported in the JSON too).  Run ``python bench.py --config
N`` for the per-config benches [B:6-12]; default is the flagship 15-class
MLP pipeline (config 2).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
BASELINE_CACHE = os.path.join(REPO, "baseline_proxy.json")

N_ROWS = int(os.environ.get("BENCH_ROWS", 500_000))
SEED = 7
MLP_LAYERS = [78, 64, 15]
MLP_MAX_ITER = 100


def _dataset(n_rows: int):
    from sntc_tpu.data import CICIDS2017_FEATURES, clean_flows, generate_frame

    raw = generate_frame(n_rows, seed=SEED)
    df = clean_flows(raw)
    return df, CICIDS2017_FEATURES


def _build_pipeline(mesh):
    from sntc_tpu.core.base import Pipeline
    from sntc_tpu.data import CICIDS2017_FEATURES
    from sntc_tpu.feature import StandardScaler, StringIndexer, VectorAssembler
    from sntc_tpu.models import MultilayerPerceptronClassifier

    return Pipeline(stages=[
        StringIndexer(inputCol="Label", outputCol="label"),
        VectorAssembler(inputCols=CICIDS2017_FEATURES, outputCol="rawFeatures"),
        StandardScaler(mesh=mesh, inputCol="rawFeatures", outputCol="features",
                       withMean=True),
        MultilayerPerceptronClassifier(
            mesh=mesh, layers=MLP_LAYERS, maxIter=MLP_MAX_ITER, seed=0
        ),
    ])


def bench_flagship(n_rows: int = N_ROWS):
    """Config 2 [B:8]: 15-class MLP pipeline, end-to-end fit wall-clock."""
    import jax

    from sntc_tpu.evaluation import MulticlassClassificationEvaluator
    from sntc_tpu.parallel.context import get_default_mesh

    df, _ = _dataset(n_rows)
    train, test = df.random_split([0.8, 0.2], seed=0)
    mesh = get_default_mesh()

    t0 = time.perf_counter()
    model = _build_pipeline(mesh).fit(train)
    cold_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    model = _build_pipeline(mesh).fit(train)
    warm_s = time.perf_counter() - t0

    out = model.transform(test)
    f1 = MulticlassClassificationEvaluator(
        metricName="macroF1", mesh=mesh
    ).evaluate(out)
    return {
        "train_s": warm_s,
        "cold_train_s": cold_s,
        "macro_f1": f1,
        "n_rows": train.num_rows,
        "platform": jax.devices()[0].platform,
    }


def measure_baseline(n_rows: int = N_ROWS):
    """CPU proxy: sklearn MLP, same topology/optimizer family/iterations."""
    from sklearn.neural_network import MLPClassifier
    from sklearn.preprocessing import StandardScaler as SkScaler

    df, feature_cols = _dataset(n_rows)
    train, _ = df.random_split([0.8, 0.2], seed=0)
    X = np.stack([train[c] for c in feature_cols], axis=1)
    labels, y = np.unique(train["Label"].astype(str), return_inverse=True)

    t0 = time.perf_counter()
    Xs = SkScaler().fit_transform(X)
    clf = MLPClassifier(
        hidden_layer_sizes=(MLP_LAYERS[1],),
        activation="logistic",
        solver="lbfgs",
        max_iter=MLP_MAX_ITER,
        tol=1e-6,
        random_state=0,
    )
    clf.fit(Xs, y)
    baseline_s = time.perf_counter() - t0

    payload = {
        "baseline": "sklearn MLPClassifier (CPU proxy for Spark-CPU; "
        "same 78-64-15 topology, logistic hiddens, lbfgs, 100 iters)",
        "train_s": baseline_s,
        "n_rows": int(train.num_rows),
        "n_iters": int(clf.n_iter_),
        "host_cpus": os.cpu_count(),
    }
    with open(BASELINE_CACHE, "w") as f:
        json.dump(payload, f, indent=1)
    return payload


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure-baseline", action="store_true")
    ap.add_argument("--rows", type=int, default=N_ROWS)
    args = ap.parse_args()

    if args.measure_baseline:
        payload = measure_baseline(args.rows)
        print(json.dumps(payload))
        return

    result = bench_flagship(args.rows)

    vs_baseline = None
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            base = json.load(f)
        # scale the cached proxy linearly if row counts differ
        scale = result["n_rows"] / max(base["n_rows"], 1)
        vs_baseline = (base["train_s"] * scale) / result["train_s"]

    print(
        json.dumps(
            {
                "metric": "cicids2017_15class_mlp_pipeline_train_wall_clock",
                "value": round(result["train_s"], 3),
                "unit": "s",
                "vs_baseline": round(vs_baseline, 2) if vs_baseline else None,
                "cold_value": round(result["cold_train_s"], 3),
                "macro_f1": round(result["macro_f1"], 4),
                "n_rows": result["n_rows"],
                "platform": result["platform"],
                "baseline": "sklearn-cpu-proxy (baseline_proxy.json)",
            }
        )
    )


if __name__ == "__main__":
    main()
